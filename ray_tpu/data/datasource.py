"""Datasources: lazy read tasks + file-based write paths.

Reference: ray ``python/ray/data/datasource/`` — a ``Datasource`` yields
``ReadTask``s (serializable zero-arg callables producing blocks) so reads
execute *inside remote tasks*, in parallel, instead of on the driver; writes
emit one file per block via remote tasks.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .block import Block


class ReadTask:
    """A serializable unit of read work (reference ``ReadTask``:
    ``python/ray/data/datasource/datasource.py``)."""

    def __init__(self, fn: Callable[[], Block], metadata: Optional[dict] = None):
        self._fn = fn
        self.metadata = metadata or {}

    def __call__(self) -> Block:
        return self._fn()


class Datasource:
    """ABC: implement ``get_read_tasks(parallelism)``."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


# ----------------------------------------------------------------- in-memory
class ItemsDatasource(Datasource):
    def __init__(self, items: Sequence[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = max(1, min(parallelism, len(items) or 1))
        size = (len(items) + n - 1) // n
        tasks = []
        for i in range(n):
            chunk = items[i * size : (i + 1) * size]
            if not chunk and items:
                continue  # ceil-division can leave empty trailing chunks
            tasks.append(
                ReadTask(lambda c=chunk: list(c), {"num_rows": len(chunk)})
            )
        return tasks


class RangeDatasource(Datasource):
    def __init__(self, n: int):
        self._n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self._n
        k = max(1, min(parallelism, n or 1))
        size = (n + k - 1) // k
        tasks = []
        for i in range(k):
            lo, hi = i * size, min((i + 1) * size, n)
            if lo >= hi:
                continue
            tasks.append(
                ReadTask(
                    lambda a=lo, b=hi: list(range(a, b)),
                    {"num_rows": hi - lo},
                )
            )
        return tasks


class NumpyDatasource(Datasource):
    """Columnar dict of arrays → row blocks."""

    def __init__(self, arrays: Dict[str, np.ndarray]):
        self._arrays = {k: np.asarray(v) for k, v in arrays.items()}

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        arrays = self._arrays
        n_rows = len(next(iter(arrays.values()))) if arrays else 0
        k = max(1, min(parallelism, n_rows or 1))
        size = (n_rows + k - 1) // k
        tasks = []
        for i in range(k):
            lo, hi = i * size, min((i + 1) * size, n_rows)
            if lo >= hi:
                continue
            chunk = {c: v[lo:hi] for c, v in arrays.items()}
            tasks.append(
                ReadTask(
                    lambda ch=chunk: [
                        {c: v[j] for c, v in ch.items()}
                        for j in range(len(next(iter(ch.values()))))
                    ],
                    {"num_rows": hi - lo},
                )
            )
        return tasks


# --------------------------------------------------------------------- files
def _expand_paths(path: str, suffix: str = "") -> List[str]:
    """A path may be a file, a directory, or a glob."""
    if os.path.isdir(path):
        return sorted(
            _glob.glob(os.path.join(path, f"*{suffix}" if suffix else "*"))
        )
    matches = sorted(_glob.glob(path))
    return matches or [path]


class ParquetDatasource(Datasource):
    """One read task per file (row-group granularity when a single file)."""

    def __init__(self, path: str, columns: Optional[List[str]] = None):
        self._paths = _expand_paths(path, ".parquet")
        self._columns = columns

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        cols = self._columns
        if len(self._paths) == 1 and parallelism > 1:
            import pyarrow.parquet as pq

            # Split one file by row group so a single large file still
            # parallelizes.
            path = self._paths[0]
            n_groups = pq.ParquetFile(path).num_row_groups
            tasks = []
            for g in range(n_groups):
                def read(p=path, grp=g):
                    import pyarrow.parquet as pq  # noqa: PLC0415

                    return pq.ParquetFile(p).read_row_group(
                        grp, columns=cols
                    ).to_pylist()

                tasks.append(ReadTask(read, {"path": path, "row_group": g}))
            return tasks
        tasks = []
        for path in self._paths:
            def read(p=path):
                import pyarrow.parquet as pq  # noqa: PLC0415

                return pq.read_table(p, columns=cols).to_pylist()

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class CSVDatasource(Datasource):
    def __init__(self, path: str):
        self._paths = _expand_paths(path, ".csv")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                import csv  # noqa: PLC0415

                with open(p) as f:
                    return list(csv.DictReader(f))

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class JSONDatasource(Datasource):
    """JSON-lines files."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path, ".jsonl")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                import json  # noqa: PLC0415

                out = []
                with open(p) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            out.append(json.loads(line))
                return out

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class BinaryFilesDatasource(Datasource):
    """Rows of ``{"path", "bytes"}`` — the image/webdataset substrate."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        k = max(1, min(parallelism, len(self._paths) or 1))
        size = (len(self._paths) + k - 1) // k
        tasks = []
        for i in range(k):
            chunk = self._paths[i * size : (i + 1) * size]
            if not chunk:
                continue

            def read(paths=chunk):
                out = []
                for p in paths:
                    with open(p, "rb") as f:
                        out.append({"path": p, "bytes": f.read()})
                return out

            tasks.append(ReadTask(read, {"num_files": len(chunk)}))
        return tasks


class TextDatasource(Datasource):
    """One row per line across the matched files."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                with open(p) as f:
                    return [line.rstrip("\n") for line in f]

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


# -------------------------------------------------------------------- writes
def write_block_parquet(block: Block, path: str) -> str:
    import pyarrow as pa
    import pyarrow.parquet as pq

    rows = [r if isinstance(r, dict) else {"value": r} for r in block]
    pq.write_table(pa.Table.from_pylist(rows), path)
    return path


def write_block_csv(block: Block, path: str) -> str:
    import csv

    rows = [r if isinstance(r, dict) else {"value": r} for r in block]
    with open(path, "w", newline="") as f:
        if rows:
            writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
            writer.writeheader()
            writer.writerows(rows)
    return path


def write_block_json(block: Block, path: str) -> str:
    import json

    with open(path, "w") as f:
        for r in block:
            f.write(json.dumps(r, default=str) + "\n")
    return path
