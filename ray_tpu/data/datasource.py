"""Datasources: lazy read tasks + file-based write paths.

Reference: ray ``python/ray/data/datasource/`` — a ``Datasource`` yields
``ReadTask``s (serializable zero-arg callables producing blocks) so reads
execute *inside remote tasks*, in parallel, instead of on the driver; writes
emit one file per block via remote tasks.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .block import Block


class ReadTask:
    """A serializable unit of read work (reference ``ReadTask``:
    ``python/ray/data/datasource/datasource.py``)."""

    def __init__(self, fn: Callable[[], Block], metadata: Optional[dict] = None):
        self._fn = fn
        self.metadata = metadata or {}

    def __call__(self) -> Block:
        return self._fn()


class Datasource:
    """ABC: implement ``get_read_tasks(parallelism)``."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


# ----------------------------------------------------------------- in-memory
class ItemsDatasource(Datasource):
    def __init__(self, items: Sequence[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = max(1, min(parallelism, len(items) or 1))
        size = (len(items) + n - 1) // n
        tasks = []
        for i in range(n):
            chunk = items[i * size : (i + 1) * size]
            if not chunk and items:
                continue  # ceil-division can leave empty trailing chunks
            tasks.append(
                ReadTask(lambda c=chunk: list(c), {"num_rows": len(chunk)})
            )
        return tasks


class RangeDatasource(Datasource):
    def __init__(self, n: int):
        self._n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self._n
        k = max(1, min(parallelism, n or 1))
        size = (n + k - 1) // k
        tasks = []
        for i in range(k):
            lo, hi = i * size, min((i + 1) * size, n)
            if lo >= hi:
                continue
            tasks.append(
                ReadTask(
                    lambda a=lo, b=hi: list(range(a, b)),
                    {"num_rows": hi - lo},
                )
            )
        return tasks


class NumpyDatasource(Datasource):
    """Columnar dict of arrays → row blocks."""

    def __init__(self, arrays: Dict[str, np.ndarray]):
        self._arrays = {k: np.asarray(v) for k, v in arrays.items()}

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        arrays = self._arrays
        n_rows = len(next(iter(arrays.values()))) if arrays else 0
        k = max(1, min(parallelism, n_rows or 1))
        size = (n_rows + k - 1) // k
        tasks = []
        for i in range(k):
            lo, hi = i * size, min((i + 1) * size, n_rows)
            if lo >= hi:
                continue
            chunk = {c: v[lo:hi] for c, v in arrays.items()}
            tasks.append(
                ReadTask(
                    lambda ch=chunk: [
                        {c: v[j] for c, v in ch.items()}
                        for j in range(len(next(iter(ch.values()))))
                    ],
                    {"num_rows": hi - lo},
                )
            )
        return tasks


# --------------------------------------------------------------------- files
def _expand_paths(path: str, suffix: str = "") -> List[str]:
    """A path may be a file, a directory, or a glob — local or any
    registered URI scheme (``file://``, ``memory://``, mounted ``gs://``;
    see ``data/filesystem.py``)."""
    from .filesystem import resolve

    fs, p = resolve(path)
    if fs.isdir(p):
        return fs.glob(fs.join(p, f"*{suffix}" if suffix else "*"))
    matches = fs.glob(p)
    return matches or [path]


def _local(path: str) -> str:
    """Materialize a possibly-remote file as a real OS path (identity for
    local paths).  Runs INSIDE read tasks, on whichever worker executes
    them."""
    from .filesystem import ensure_local

    return ensure_local(path)


def _table_to_columnar(table):
    """pyarrow Table → ColumnarBlock (numpy columns; zero-copy where the
    arrow buffer layout allows, object arrays for strings/nested)."""
    from .block import ColumnarBlock

    cols = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            cols[name] = col.to_numpy(zero_copy_only=False)
        except Exception:  # noqa: BLE001 — exotic nested types
            cols[name] = np.asarray(col.to_pylist(), dtype=object)
    return ColumnarBlock(cols)


class ParquetReadTask(ReadTask):
    """Parquet read with pushdown hooks: the plan optimizer can narrow the
    read to a column subset (projection pushdown) and/or attach a row
    predicate (filter pushdown) — reference
    ``data/_internal/logical/rules/`` projection/filter pushdown into
    ParquetDatasource."""

    def __init__(self, path: str, row_group: Optional[int] = None,
                 columns: Optional[List[str]] = None,
                 filters: Optional[list] = None,
                 metadata: Optional[dict] = None):
        self.path = path
        self.row_group = row_group
        self.columns = columns
        self.filters = filters
        super().__init__(self._read, metadata)

    def with_projection(self, cols: List[str]) -> "ParquetReadTask":
        merged = (
            [c for c in self.columns if c in cols]
            if self.columns is not None
            else list(cols)
        )
        return ParquetReadTask(
            self.path, self.row_group, merged, self.filters, dict(self.metadata)
        )

    def with_predicate(self, filters: list) -> "ParquetReadTask":
        return ParquetReadTask(
            self.path, self.row_group, self.columns,
            (self.filters or []) + list(filters), dict(self.metadata),
        )

    def _read(self):
        import pyarrow.parquet as pq

        path = _local(self.path)
        if self.filters is not None:
            import pyarrow.compute as pc
            import pyarrow.dataset as pads

            # Dataset API: row-exact predicate evaluation during the scan.
            expr = None
            for col, op, val in self.filters:
                field = pc.field(col)
                term = {
                    "==": field == val, "!=": field != val,
                    ">": field > val, ">=": field >= val,
                    "<": field < val, "<=": field <= val,
                }[op]
                expr = term if expr is None else (expr & term)
            ds = pads.dataset(path)
            if self.row_group is not None:
                frag = list(ds.get_fragments())[0]
                frag = frag.subset(row_group_ids=[self.row_group])
                table = frag.to_table(filter=expr, columns=self.columns)
            else:
                table = ds.to_table(filter=expr, columns=self.columns)
            return _table_to_columnar(table)
        if self.row_group is not None:
            table = pq.ParquetFile(path).read_row_group(
                self.row_group, columns=self.columns
            )
        else:
            table = pq.read_table(path, columns=self.columns)
        return _table_to_columnar(table)

    def __reduce__(self):
        return (
            ParquetReadTask,
            (self.path, self.row_group, self.columns, self.filters,
             self.metadata),
        )


class ParquetDatasource(Datasource):
    """One read task per file (row-group granularity when a single file).
    Emits columnar blocks."""

    def __init__(self, path: str, columns: Optional[List[str]] = None):
        self._paths = _expand_paths(path, ".parquet")
        self._columns = columns

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        cols = self._columns
        if len(self._paths) == 1 and parallelism > 1:
            import pyarrow.parquet as pq

            # Split one file by row group so a single large file still
            # parallelizes.  (Metadata probe localizes remote files once
            # on the driver; the row-group reads localize per task.)
            path = self._paths[0]
            n_groups = pq.ParquetFile(_local(path)).num_row_groups
            return [
                ParquetReadTask(
                    path, g, cols, None, {"path": path, "row_group": g}
                )
                for g in range(n_groups)
            ]
        return [
            ParquetReadTask(path, None, cols, None, {"path": path})
            for path in self._paths
        ]


class CSVDatasource(Datasource):
    def __init__(self, path: str):
        self._paths = _expand_paths(path, ".csv")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                import csv  # noqa: PLC0415

                with open(_local(p)) as f:
                    return list(csv.DictReader(f))

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class JSONDatasource(Datasource):
    """JSON-lines files."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path, ".jsonl")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                import json  # noqa: PLC0415

                out = []
                with open(_local(p)) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            out.append(json.loads(line))
                return out

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class TFRecordsDatasource(Datasource):
    """tf.train.Example TFRecord files, TF-free (codec in
    ``data/tfrecord.py``).  Matches both ``.tfrecord`` and ``.tfrecords``."""

    def __init__(self, path: str):
        from .filesystem import resolve

        paths = _expand_paths(path, ".tfrecord")
        if resolve(path)[0].isdir(path):
            paths = sorted(
                set(paths) | set(_expand_paths(path, ".tfrecords"))
            )
        self._paths = paths

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        from .tfrecord import read_tfrecord_file

        return [
            ReadTask(lambda p=p: read_tfrecord_file(_local(p)), {"path": p})
            for p in self._paths
        ]


IMAGE_SUFFIXES = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp", ".tiff")


class ImageFilesDatasource(Datasource):
    """Image files → ``{"path", "bytes"}`` rows, filtered to image
    extensions so a stray README/checksum in the directory can't fail the
    read (reference image_datasource filters the same way)."""

    def __init__(self, path: str):
        self._paths = [
            p for p in _expand_paths(path)
            if p.lower().endswith(IMAGE_SUFFIXES)
        ]

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        k = max(1, min(parallelism, len(self._paths) or 1))
        size = (len(self._paths) + k - 1) // k
        tasks = []
        for i in range(k):
            chunk = self._paths[i * size : (i + 1) * size]
            if not chunk:
                continue

            def read(paths=chunk):
                out = []
                for p in paths:
                    with open(_local(p), "rb") as f:
                        out.append({"path": p, "bytes": f.read()})
                return out

            tasks.append(ReadTask(read, {"num_files": len(chunk)}))
        return tasks


class BinaryFilesDatasource(Datasource):
    """Rows of ``{"path", "bytes"}`` — the image/webdataset substrate."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        k = max(1, min(parallelism, len(self._paths) or 1))
        size = (len(self._paths) + k - 1) // k
        tasks = []
        for i in range(k):
            chunk = self._paths[i * size : (i + 1) * size]
            if not chunk:
                continue

            def read(paths=chunk):
                out = []
                for p in paths:
                    with open(_local(p), "rb") as f:
                        out.append({"path": p, "bytes": f.read()})
                return out

            tasks.append(ReadTask(read, {"num_files": len(chunk)}))
        return tasks


class TextDatasource(Datasource):
    """One row per line across the matched files."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                with open(_local(p)) as f:
                    return [line.rstrip("\n") for line in f]

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class AvroDatasource(Datasource):
    """Avro object-container files, dependency-free (codec in
    ``data/avro.py``; reference ``avro_datasource.py`` uses fastavro)."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path, ".avro")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        from .avro import read_avro_file

        return [
            ReadTask(lambda p=p: read_avro_file(_local(p)), {"path": p})
            for p in self._paths
        ]


def decode_wds_member(name: str, data: bytes):
    """WebDataset per-extension auto-decode: .json → object, .txt → str,
    .cls → int label, everything else (incl. images) raw bytes."""
    if name.endswith(".json"):
        import json

        return json.loads(data)
    if name.endswith((".txt", ".text")):
        return data.decode()
    if name.endswith(".cls"):
        return int(data.decode().strip())
    return data


class WebDatasetDatasource(Datasource):
    """POSIX-tar sample archives (reference ``webdataset_datasource.py``,
    which wraps the ``webdataset`` package; hand-rolled on stdlib tarfile
    here).  Members sharing a basename form one sample: ``x/y.jpg`` +
    ``x/y.cls`` + ``x/y.json`` → one row ``{"__key__": "x/y", "jpg": …,
    "cls": …, "json": …}``."""

    def __init__(self, path: str):
        from .filesystem import resolve

        paths = _expand_paths(path, ".tar")
        if resolve(path)[0].isdir(path):
            paths = sorted(
                set(paths)
                | set(_expand_paths(path, ".tgz"))
                | set(_expand_paths(path, ".tar.gz"))
            )
        self._paths = paths

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        return [
            ReadTask(lambda p=p: self._read_tar(p), {"path": p})
            for p in self._paths
        ]

    @staticmethod
    def _read_tar(path: str) -> List[dict]:
        import tarfile

        rows: List[dict] = []
        current_key: Optional[str] = None
        row: dict = {}
        mode = "r:gz" if path.endswith((".tgz", ".tar.gz")) else "r"
        with tarfile.open(_local(path), mode) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                base = member.name
                # Sample key = path up to the FIRST dot of the basename
                # (dots in directory names don't split).
                slash = base.rfind("/") + 1
                dot = base.find(".", slash)
                if dot == -1:
                    key, ext = base, "bin"
                else:
                    key, ext = base[:dot], base[dot + 1 :]
                data = tf.extractfile(member).read()
                if key != current_key:
                    if current_key is not None:
                        rows.append(row)
                    current_key, row = key, {"__key__": key}
                row[ext] = decode_wds_member(base, data)
            if current_key is not None:
                rows.append(row)
        return rows


class AudioDatasource(Datasource):
    """PCM WAV files → ``{"audio": [samples, channels] float32 in [-1,1],
    "sample_rate", "path"}`` rows.  Reference ``audio_datasource.py``
    decodes via the ``soundfile`` package (absent here); WAV framing +
    PCM decode are stdlib (``wave``) + numpy, which covers the dominant
    ingest format without a native audio dependency."""

    SUFFIXES = (".wav", ".wave")

    def __init__(self, path: str):
        self._paths = [
            p for p in _expand_paths(path)
            if p.lower().endswith(self.SUFFIXES)
        ]
        if not self._paths:
            # Loud failure beats a silently empty dataset on a typo'd
            # path or a directory with no matching files.
            raise FileNotFoundError(
                f"no {'/'.join(self.SUFFIXES)} files at {path!r}"
            )

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        return [
            ReadTask(lambda p=p: [self._read_wav(p)], {"path": p})
            for p in self._paths
        ]

    @staticmethod
    def _read_wav(path: str) -> dict:
        import wave

        with wave.open(_local(path), "rb") as w:
            n_ch = w.getnchannels()
            width = w.getsampwidth()
            rate = w.getframerate()
            raw = w.readframes(w.getnframes())
        if width == 2:
            arr = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
        elif width == 4:
            arr = np.frombuffer(raw, np.int32).astype(np.float32) / 2147483648.0
        elif width == 1:  # unsigned 8-bit PCM
            arr = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
        else:
            raise ValueError(f"{path}: unsupported PCM sample width {width}")
        return {
            "audio": arr.reshape(-1, n_ch),
            "sample_rate": rate,
            "path": path,
        }


class VideoDatasource(Datasource):
    """Video files → one ``{"frame": HxWx3 uint8 RGB, "frame_index",
    "path"}`` row per frame via OpenCV (reference ``video_datasource.py``
    uses pyav; cv2 is what this image ships).  ``stride`` subsamples
    frames at read time (the usual ingest decimation)."""

    SUFFIXES = (".mp4", ".avi", ".mkv", ".mov", ".webm")

    def __init__(self, path: str, stride: int = 1):
        self._paths = [
            p for p in _expand_paths(path)
            if p.lower().endswith(self.SUFFIXES)
        ]
        if not self._paths:
            raise FileNotFoundError(
                f"no {'/'.join(self.SUFFIXES)} files at {path!r}"
            )
        self._stride = max(1, stride)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        stride = self._stride
        return [
            ReadTask(lambda p=p: self._read_video(p, stride), {"path": p})
            for p in self._paths
        ]

    @staticmethod
    def _read_video(path: str, stride: int) -> List[dict]:
        import cv2

        cap = cv2.VideoCapture(_local(path))
        if not cap.isOpened():
            raise ValueError(f"{path}: cv2 cannot open video")
        rows = []
        i = 0
        try:
            while True:
                ok, frame = cap.read()
                if not ok:
                    break
                if i % stride == 0:
                    rows.append({
                        "frame": cv2.cvtColor(frame, cv2.COLOR_BGR2RGB),
                        "frame_index": i,
                        "path": path,
                    })
                i += 1
        finally:
            cap.release()
        return rows


class SQLDatasource(Datasource):
    """Rows from any DB-API 2.0 database (reference ``sql_datasource.py``).

    ``connection_factory`` must be a picklable zero-arg callable returning
    a DB-API connection — it is invoked *inside* the read task so each
    worker opens its own connection.  With ``shard_keys`` the query is
    split into ``parallelism`` tasks via ``WHERE mod(hash, n) = i``-style
    sharding on the key column (falls back to ``%`` arithmetic, which
    every DB-API engine can evaluate)."""

    def __init__(self, sql: str, connection_factory: Callable,
                 shard_key: Optional[str] = None):
        self._sql = sql
        self._factory = connection_factory
        self._shard_key = shard_key

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory, sql = self._factory, self._sql

        def run_query(query: str) -> List[dict]:
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(query)
                names = [d[0] for d in cur.description]
                return [dict(zip(names, row)) for row in cur.fetchall()]
            finally:
                conn.close()

        if self._shard_key is None or parallelism <= 1:
            return [ReadTask(lambda: run_query(sql), {"sql": sql})]
        key = self._shard_key
        tasks = []
        for i in range(parallelism):
            # Non-negative modulo (dividend-signed `%` maps negative keys
            # to no shard on most engines); NULL keys land in shard 0 so
            # no row silently vanishes.
            pred = f"((({key} % {parallelism}) + {parallelism}) " \
                   f"% {parallelism}) = {i}"
            if i == 0:
                pred = f"({pred} OR {key} IS NULL)"
            sharded = f"SELECT * FROM ({sql}) AS __t WHERE {pred}"
            tasks.append(
                ReadTask(lambda q=sharded: run_query(q), {"sql": sharded})
            )
        return tasks


# Writes live in datasink.py (Datasink ABC + format sinks) — every
# Dataset.write_* funnels through Dataset.write_datasink.
