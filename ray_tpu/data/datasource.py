"""Datasources: lazy read tasks + file-based write paths.

Reference: ray ``python/ray/data/datasource/`` — a ``Datasource`` yields
``ReadTask``s (serializable zero-arg callables producing blocks) so reads
execute *inside remote tasks*, in parallel, instead of on the driver; writes
emit one file per block via remote tasks.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .block import Block


class ReadTask:
    """A serializable unit of read work (reference ``ReadTask``:
    ``python/ray/data/datasource/datasource.py``)."""

    def __init__(self, fn: Callable[[], Block], metadata: Optional[dict] = None):
        self._fn = fn
        self.metadata = metadata or {}

    def __call__(self) -> Block:
        return self._fn()


class Datasource:
    """ABC: implement ``get_read_tasks(parallelism)``."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


# ----------------------------------------------------------------- in-memory
class ItemsDatasource(Datasource):
    def __init__(self, items: Sequence[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = max(1, min(parallelism, len(items) or 1))
        size = (len(items) + n - 1) // n
        tasks = []
        for i in range(n):
            chunk = items[i * size : (i + 1) * size]
            if not chunk and items:
                continue  # ceil-division can leave empty trailing chunks
            tasks.append(
                ReadTask(lambda c=chunk: list(c), {"num_rows": len(chunk)})
            )
        return tasks


class RangeDatasource(Datasource):
    def __init__(self, n: int):
        self._n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self._n
        k = max(1, min(parallelism, n or 1))
        size = (n + k - 1) // k
        tasks = []
        for i in range(k):
            lo, hi = i * size, min((i + 1) * size, n)
            if lo >= hi:
                continue
            tasks.append(
                ReadTask(
                    lambda a=lo, b=hi: list(range(a, b)),
                    {"num_rows": hi - lo},
                )
            )
        return tasks


class NumpyDatasource(Datasource):
    """Columnar dict of arrays → row blocks."""

    def __init__(self, arrays: Dict[str, np.ndarray]):
        self._arrays = {k: np.asarray(v) for k, v in arrays.items()}

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        arrays = self._arrays
        n_rows = len(next(iter(arrays.values()))) if arrays else 0
        k = max(1, min(parallelism, n_rows or 1))
        size = (n_rows + k - 1) // k
        tasks = []
        for i in range(k):
            lo, hi = i * size, min((i + 1) * size, n_rows)
            if lo >= hi:
                continue
            chunk = {c: v[lo:hi] for c, v in arrays.items()}
            tasks.append(
                ReadTask(
                    lambda ch=chunk: [
                        {c: v[j] for c, v in ch.items()}
                        for j in range(len(next(iter(ch.values()))))
                    ],
                    {"num_rows": hi - lo},
                )
            )
        return tasks


# --------------------------------------------------------------------- files
def _expand_paths(path: str, suffix: str = "") -> List[str]:
    """A path may be a file, a directory, or a glob."""
    if os.path.isdir(path):
        return sorted(
            _glob.glob(os.path.join(path, f"*{suffix}" if suffix else "*"))
        )
    matches = sorted(_glob.glob(path))
    return matches or [path]


def _table_to_columnar(table):
    """pyarrow Table → ColumnarBlock (numpy columns; zero-copy where the
    arrow buffer layout allows, object arrays for strings/nested)."""
    from .block import ColumnarBlock

    cols = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            cols[name] = col.to_numpy(zero_copy_only=False)
        except Exception:  # noqa: BLE001 — exotic nested types
            cols[name] = np.asarray(col.to_pylist(), dtype=object)
    return ColumnarBlock(cols)


class ParquetReadTask(ReadTask):
    """Parquet read with pushdown hooks: the plan optimizer can narrow the
    read to a column subset (projection pushdown) and/or attach a row
    predicate (filter pushdown) — reference
    ``data/_internal/logical/rules/`` projection/filter pushdown into
    ParquetDatasource."""

    def __init__(self, path: str, row_group: Optional[int] = None,
                 columns: Optional[List[str]] = None,
                 filters: Optional[list] = None,
                 metadata: Optional[dict] = None):
        self.path = path
        self.row_group = row_group
        self.columns = columns
        self.filters = filters
        super().__init__(self._read, metadata)

    def with_projection(self, cols: List[str]) -> "ParquetReadTask":
        merged = (
            [c for c in self.columns if c in cols]
            if self.columns is not None
            else list(cols)
        )
        return ParquetReadTask(
            self.path, self.row_group, merged, self.filters, dict(self.metadata)
        )

    def with_predicate(self, filters: list) -> "ParquetReadTask":
        return ParquetReadTask(
            self.path, self.row_group, self.columns,
            (self.filters or []) + list(filters), dict(self.metadata),
        )

    def _read(self):
        import pyarrow.parquet as pq

        if self.filters is not None:
            import pyarrow.compute as pc
            import pyarrow.dataset as pads

            # Dataset API: row-exact predicate evaluation during the scan.
            expr = None
            for col, op, val in self.filters:
                field = pc.field(col)
                term = {
                    "==": field == val, "!=": field != val,
                    ">": field > val, ">=": field >= val,
                    "<": field < val, "<=": field <= val,
                }[op]
                expr = term if expr is None else (expr & term)
            ds = pads.dataset(self.path)
            if self.row_group is not None:
                frag = list(ds.get_fragments())[0]
                frag = frag.subset(row_group_ids=[self.row_group])
                table = frag.to_table(filter=expr, columns=self.columns)
            else:
                table = ds.to_table(filter=expr, columns=self.columns)
            return _table_to_columnar(table)
        if self.row_group is not None:
            table = pq.ParquetFile(self.path).read_row_group(
                self.row_group, columns=self.columns
            )
        else:
            table = pq.read_table(self.path, columns=self.columns)
        return _table_to_columnar(table)

    def __reduce__(self):
        return (
            ParquetReadTask,
            (self.path, self.row_group, self.columns, self.filters,
             self.metadata),
        )


class ParquetDatasource(Datasource):
    """One read task per file (row-group granularity when a single file).
    Emits columnar blocks."""

    def __init__(self, path: str, columns: Optional[List[str]] = None):
        self._paths = _expand_paths(path, ".parquet")
        self._columns = columns

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        cols = self._columns
        if len(self._paths) == 1 and parallelism > 1:
            import pyarrow.parquet as pq

            # Split one file by row group so a single large file still
            # parallelizes.
            path = self._paths[0]
            n_groups = pq.ParquetFile(path).num_row_groups
            return [
                ParquetReadTask(
                    path, g, cols, None, {"path": path, "row_group": g}
                )
                for g in range(n_groups)
            ]
        return [
            ParquetReadTask(path, None, cols, None, {"path": path})
            for path in self._paths
        ]


class CSVDatasource(Datasource):
    def __init__(self, path: str):
        self._paths = _expand_paths(path, ".csv")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                import csv  # noqa: PLC0415

                with open(p) as f:
                    return list(csv.DictReader(f))

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class JSONDatasource(Datasource):
    """JSON-lines files."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path, ".jsonl")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                import json  # noqa: PLC0415

                out = []
                with open(p) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            out.append(json.loads(line))
                return out

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


class TFRecordsDatasource(Datasource):
    """tf.train.Example TFRecord files, TF-free (codec in
    ``data/tfrecord.py``).  Matches both ``.tfrecord`` and ``.tfrecords``."""

    def __init__(self, path: str):
        paths = _expand_paths(path, ".tfrecord")
        if os.path.isdir(path):
            paths = sorted(
                set(paths) | set(_expand_paths(path, ".tfrecords"))
            )
        self._paths = paths

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        from .tfrecord import read_tfrecord_file

        return [
            ReadTask(lambda p=p: read_tfrecord_file(p), {"path": p})
            for p in self._paths
        ]


IMAGE_SUFFIXES = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp", ".tiff")


class ImageFilesDatasource(Datasource):
    """Image files → ``{"path", "bytes"}`` rows, filtered to image
    extensions so a stray README/checksum in the directory can't fail the
    read (reference image_datasource filters the same way)."""

    def __init__(self, path: str):
        self._paths = [
            p for p in _expand_paths(path)
            if p.lower().endswith(IMAGE_SUFFIXES)
        ]

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        k = max(1, min(parallelism, len(self._paths) or 1))
        size = (len(self._paths) + k - 1) // k
        tasks = []
        for i in range(k):
            chunk = self._paths[i * size : (i + 1) * size]
            if not chunk:
                continue

            def read(paths=chunk):
                out = []
                for p in paths:
                    with open(p, "rb") as f:
                        out.append({"path": p, "bytes": f.read()})
                return out

            tasks.append(ReadTask(read, {"num_files": len(chunk)}))
        return tasks


class BinaryFilesDatasource(Datasource):
    """Rows of ``{"path", "bytes"}`` — the image/webdataset substrate."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        k = max(1, min(parallelism, len(self._paths) or 1))
        size = (len(self._paths) + k - 1) // k
        tasks = []
        for i in range(k):
            chunk = self._paths[i * size : (i + 1) * size]
            if not chunk:
                continue

            def read(paths=chunk):
                out = []
                for p in paths:
                    with open(p, "rb") as f:
                        out.append({"path": p, "bytes": f.read()})
                return out

            tasks.append(ReadTask(read, {"num_files": len(chunk)}))
        return tasks


class TextDatasource(Datasource):
    """One row per line across the matched files."""

    def __init__(self, path: str):
        self._paths = _expand_paths(path)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def read(p=path):
                with open(p) as f:
                    return [line.rstrip("\n") for line in f]

            tasks.append(ReadTask(read, {"path": path}))
        return tasks


# Writes live in datasink.py (Datasink ABC + format sinks) — every
# Dataset.write_* funnels through Dataset.write_datasink.
