"""Dataset plan nodes + the plan optimizer.

Reference architecture: ray ``python/ray/data/_internal/execution/
streaming_executor.py:67`` + physical operators (``operators/map_operator.py``,
``actor_pool_map_operator.py``, ``hash_shuffle.py``).  This module holds the
LOGICAL plan pieces — stage descriptions (``MapStage`` / ``AllToAllStage`` /
``LimitStage``), the rewrite rules (fusion, pushdown, repartition elision),
the exchange substrate (``_shuffle_map`` / ``_shuffle_reduce``), and per-op
stats.  The PHYSICAL execution lives in ``streaming.py``: an operator-graph
scheduler that drives these stages as nodes with bounded input/output
queues, out-of-order completion harvesting, actor-pool autoscaling, and
dynamic block shaping.

Deliberate TPU-native semantics:
  - ordered emission is the default (``take`` and train ingest stay
    deterministic); out-of-order streaming is opt-in via
    ``ExecutionOptions(preserve_order=False)``;
  - narrow transforms are fused into a single stage (the reference's
    OperatorFusionRule) and also fused into the map phase of a following
    shuffle;
  - wide ops (shuffle/sort/groupby/repartition) are an internal barrier: a
    distributed map/reduce exchange over ``num_returns=n`` tasks.

The scheduler runs in whatever process iterates the dataset; blocks live in
the object store and move node-to-node only when a consumer pulls them.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterator, List, Optional

import numpy as np

import ray_tpu

from .block import Block
from .datasource import ReadTask

Transform = Callable[[Block], Block]


# ------------------------------------------------------------ remote helpers
def apply_chain(item, transforms: List[Transform]) -> Block:
    """Materialize one input item (ReadTask or block) through a fused
    transform chain."""
    block = item() if isinstance(item, ReadTask) else item
    for t in transforms:
        block = t(block)
    return block


@ray_tpu.remote
def _run_item(item, transforms: List[Transform]) -> Block:
    return apply_chain(item, transforms)


class HashPartition:
    """Hash-on-key partitioner.  As a plain callable it is the per-row
    generic path; ``vector_parts`` is the columnar fast path _shuffle_map
    recognizes — numeric key columns hash in a few numpy passes
    (scalar/vector equality guaranteed by block._splitmix64) instead of a
    per-row Python loop (reference: native hash_shuffle partitioning)."""

    def __init__(self, key):
        self.key = key

    def __call__(self, row, i, bidx):
        from .block import row_key, stable_hash

        return stable_hash(row_key(row, self.key))

    def vector_parts(self, block, n_out: int, bidx: int):
        from .block import hash_column

        if not isinstance(self.key, str):
            return None
        col = block.columns.get(self.key)
        if col is None:
            return None
        hashes = hash_column(col)
        if hashes is None:
            return None
        return (hashes % np.uint64(n_out)).astype(np.int64)


class RoundRobinPartition:
    """Deterministic row->partition striping (repartition)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks

    def __call__(self, row, i, bidx):
        return (bidx * 1000003 + i) % self.num_blocks

    def vector_parts(self, block, n_out: int, bidx: int):
        return (bidx * 1000003 + np.arange(len(block))) % n_out


@ray_tpu.remote
def _shuffle_map(item, transforms, n_out: int, part_fn, block_idx: int):
    """Map phase of an exchange: apply fused chain, split rows into n_out
    partitions (returned as n_out separate objects via num_returns)."""
    from .block import ColumnarBlock

    block = apply_chain(item, transforms)
    if isinstance(block, ColumnarBlock) and hasattr(part_fn, "vector_parts"):
        pidx = part_fn.vector_parts(block, n_out, block_idx)
        if pidx is not None:
            # Columnar all the way: no row materialization on the map
            # side, and reducers that do no row work (repartition)
            # re-concatenate columnar.
            from .block import partition_columnar

            parts = partition_columnar(block, pidx, n_out)
            return parts if n_out > 1 else parts[0]
    parts: List[Block] = [[] for _ in range(n_out)]
    for i, row in enumerate(block):
        parts[part_fn(row, i, block_idx) % n_out].append(row)
    # num_returns=n_out>1 splits the returned list into one object per
    # partition; num_returns=1 returns the value VERBATIM, so the single
    # partition must be returned bare or every 1-reducer exchange (e.g.
    # repartition(1)) would emit a nested [rows] block.
    return parts if n_out > 1 else parts[0]


@ray_tpu.remote
def _shuffle_reduce(reduce_fn, reducer_idx: int, *parts: Block) -> Block:
    if reduce_fn is not None and getattr(reduce_fn, "wants_blocks", False):
        # Block-aware reducers (groupby aggregation) see the raw parts:
        # columnar parts aggregate vectorized instead of being rowified
        # here first.
        return reduce_fn(list(parts), reducer_idx)
    if reduce_fn is None:
        # Pure concatenation exchanges (repartition) stay columnar when
        # every non-empty part is (parquet -> repartition -> write never
        # rowifies).
        from .block import concat_columnar

        cat = concat_columnar(parts)
        if cat is not None:
            return cat
    rows = [r for p in parts for r in p]
    if reduce_fn is not None:
        rows = reduce_fn(rows, reducer_idx)
    return rows


class _MapWorker:
    """Actor applying a fused chain (reference ``actor_pool_map_operator``'s
    ``_MapWorker``); holds user state (e.g. a loaded model) across blocks."""

    def __init__(self, transforms: List[Transform]):
        self._transforms = transforms

    def apply(self, item) -> Block:
        return apply_chain(item, self._transforms)

    def prepare_evict(self) -> None:
        """Checkpoint-then-evict hook (docs/scheduling.md): a map chain
        holds no durable pool state — in-flight blocks are simply
        re-dispatched by the streaming scheduler after the kill — but a
        stateful user transform (loaded model, buffered writer) gets its
        flush if it exposes ``prepare_evict`` itself."""
        for t in self._transforms:
            fn = getattr(t, "prepare_evict", None)
            if callable(fn):
                fn()


class ActorPoolStrategy:
    """``map_batches(..., compute=ActorPoolStrategy(size=4))`` (reference
    ``python/ray/data/_internal/compute.py``).

    ``min_size``/``max_size`` turn the pool into an autoscaling one under
    the streaming scheduler: it grows toward ``max_size`` on sustained
    input-queue pressure and shrinks back to ``min_size`` when actors
    starve.  Plain ``size`` pins both bounds (fixed pool, the round-1
    behavior)."""

    def __init__(
        self,
        size: int = 2,
        max_tasks_in_flight_per_actor: int = 2,
        num_tpus: float = 0,
        num_cpus: Optional[float] = None,
        min_size: Optional[int] = None,
        max_size: Optional[int] = None,
    ):
        self.size = size
        self.min_size = min_size if min_size is not None else size
        self.max_size = max_size if max_size is not None else max(
            self.min_size, size
        )
        if self.min_size < 1 or self.max_size < self.min_size:
            raise ValueError(
                f"invalid pool bounds: min_size={self.min_size} "
                f"max_size={self.max_size}"
            )
        self.max_tasks_in_flight_per_actor = max_tasks_in_flight_per_actor
        self.num_tpus = num_tpus
        self.num_cpus = num_cpus


# ------------------------------------------------------------------- stages
class OpStats:
    """Per-operator execution accounting.

    ``wall_s`` measures OPERATOR time — first input/launch to last output
    *produced* (completion harvested by the scheduler), not to last output
    consumed downstream.  (The former generator chain folded downstream
    consume time into every upstream ``yield``; the operator-graph
    scheduler closes the interval at production.)"""

    QUEUE_WAIT_SAMPLE_CAP = 4096

    def __init__(self, name: str):
        self.name = name
        self.num_tasks = 0
        self.wall_s = 0.0
        # Streaming-scheduler extensions (zeros under barrier stages).
        self.queue_wait_s: List[float] = []  # per-block input-queue waits
        self.straggler_wait_s = 0.0  # scheduler blocked on this op's tasks
        self.blocks_emitted = 0
        self.blocks_split = 0
        self.blocks_coalesced = 0
        self.autoscale_up_events = 0
        self.autoscale_down_events = 0
        # Autoscaling pools: TARGET size (actor handles held).  Actor
        # creation is async, so a just-spawned entry may still be starting.
        self.pool_size = 0
        self.pool_size_peak = 0
        # Every pool-size change in order (ends with 0 at teardown):
        # lets tests/stats assert "reached max_size, returned to min_size"
        # without sampling races.
        self.pool_size_timeline: List[int] = []
        # Cancel REQUESTS issued for this op's in-flight tasks on early
        # exit.  ray_tpu.cancel is best-effort: an already-executing task
        # runs to completion, so this is not a count of tasks killed.
        self.tasks_cancel_requested = 0

    def add_queue_wait(self, dt: float):
        if len(self.queue_wait_s) < self.QUEUE_WAIT_SAMPLE_CAP:
            self.queue_wait_s.append(dt)

    def queue_wait_pct(self, q: float) -> float:
        if not self.queue_wait_s:
            return 0.0
        s = sorted(self.queue_wait_s)
        return s[min(len(s) - 1, int(q * len(s)))]

    def summary(self) -> str:
        parts = [
            f"{self.name}: {self.num_tasks} tasks, {self.wall_s:.3f}s wall",
            f"queue wait p50/p95 {self.queue_wait_pct(0.5) * 1e3:.1f}/"
            f"{self.queue_wait_pct(0.95) * 1e3:.1f}ms",
            f"{self.blocks_emitted} blocks out",
        ]
        if self.straggler_wait_s:
            parts.append(f"straggler wait {self.straggler_wait_s:.3f}s")
        if self.blocks_split or self.blocks_coalesced:
            parts.append(
                f"split/coalesced {self.blocks_split}/{self.blocks_coalesced}"
            )
        if self.autoscale_up_events or self.autoscale_down_events:
            parts.append(
                f"autoscale +{self.autoscale_up_events}/"
                f"-{self.autoscale_down_events} "
                f"(peak {self.pool_size_peak})"
            )
        if self.tasks_cancel_requested:
            parts.append(f"{self.tasks_cancel_requested} cancel requested")
        return ", ".join(parts)

    def __repr__(self):
        return f"{self.name}: {self.num_tasks} tasks, {self.wall_s:.3f}s"


class MapStage:
    """Fused narrow transforms executed by tasks (or an actor pool).

    ``projection`` / ``predicate`` mark pushdown-eligible stages (set by
    ``select_columns`` / ``filter(predicate=...)``) for the plan optimizer.
    """

    def __init__(
        self,
        transforms: List[Transform],
        names: Optional[List[str]] = None,
        compute: Optional[ActorPoolStrategy] = None,
    ):
        self.transforms = list(transforms)
        self.names = list(names or [])
        self.compute = compute
        self.projection: Optional[List[str]] = None
        self.predicate: Optional[list] = None

    @property
    def name(self) -> str:
        return "+".join(self.names) if self.names else "Map"

    def fuse(self, other: "MapStage") -> Optional["MapStage"]:
        """Adjacent task-compute map stages fuse into one."""
        if self.compute is not None or other.compute is not None:
            return None
        return MapStage(
            self.transforms + other.transforms, self.names + other.names
        )


class AllToAllStage:
    """Internal-barrier exchange: consumes every upstream ref, emits
    reducer outputs (hash shuffle substrate for shuffle/sort/groupby/
    repartition)."""

    def __init__(
        self,
        name: str,
        n_out: Optional[int],
        part_fn: Callable,
        reduce_fn: Optional[Callable] = None,
        prepare: Optional[Callable[[List], dict]] = None,
        fused_transforms: Optional[List[Transform]] = None,
        reverse_out: bool = False,
    ):
        self.name = name
        self.n_out = n_out
        self.part_fn = part_fn
        self.reduce_fn = reduce_fn
        # Optional driver-side hook run on the materialized input refs
        # before the exchange (e.g. sort boundary sampling); returns extra
        # kwargs threaded into part_fn via functools.partial.
        self.prepare = prepare
        self.fused_transforms = list(fused_transforms or [])
        # Emit reducer outputs in reverse index order (descending sort).
        self.reverse_out = reverse_out

    def with_fused(self, transforms: List[Transform]) -> "AllToAllStage":
        """Copy with a fused upstream chain — stages are shared between
        derived Datasets, so fusion must never mutate in place."""
        return AllToAllStage(
            self.name,
            self.n_out,
            self.part_fn,
            self.reduce_fn,
            self.prepare,
            transforms,
            self.reverse_out,
        )

    def run(self, upstream: Iterator, stats: List[OpStats]) -> Iterator:
        st = OpStats(self.name)
        stats.append(st)
        t0 = time.perf_counter()
        items = list(upstream)  # barrier
        n_out = self.n_out or max(1, len(items))
        part_fn = self.part_fn
        if self.prepare is not None:
            # Materialize inputs for sampling (refs only; sampling getter
            # decides what to fetch).
            refs = _ensure_refs(items, self.fused_transforms)
            items = refs
            extra = self.prepare(refs)
            if extra:
                import functools

                part_fn = functools.partial(part_fn, **extra)
            fused: List[Transform] = []
        else:
            fused = self.fused_transforms
        map_out = []
        for idx, item in enumerate(items):
            st.num_tasks += 1
            refs = _shuffle_map.options(num_returns=n_out).remote(
                item, fused, n_out, part_fn, idx
            )
            if n_out == 1:
                refs = [refs]
            map_out.append(refs)
        order = range(n_out - 1, -1, -1) if self.reverse_out else range(n_out)
        for j in order:
            st.num_tasks += 1
            parts_j = [map_out[i][j] for i in range(len(map_out))]
            st.wall_s = time.perf_counter() - t0
            yield _shuffle_reduce.remote(self.reduce_fn, j, *parts_j)
        st.wall_s = time.perf_counter() - t0


class LimitStage:
    """Global row limit (plan node; executed by the scheduler's limit
    operator).  When the limit is satisfied the scheduler cancels every
    still-in-flight upstream task and tears down actor pools — early-exit
    cancellation, not just launch-stoppage."""

    def __init__(self, n: int):
        self.n = n

    @property
    def name(self) -> str:
        return f"Limit[{self.n}]"


def _ensure_refs(items: List[Any], transforms: List[Transform]) -> List:
    """Convert any ReadTasks/plain items into block refs (applying a fused
    chain remotely)."""
    out = []
    for item in items:
        if isinstance(item, ray_tpu.ObjectRef) and not transforms:
            out.append(item)
        else:
            out.append(_run_item.remote(item, transforms))
    return out


class StreamingExecutor:
    """Facade over the operator-graph scheduler (``streaming.py``): the
    optimized plan's stages become operator nodes with bounded input/
    output queues, driven by one completion-harvesting scheduler loop
    instead of a chain of head-of-line-blocking generators."""

    def __init__(self, inputs: List[Any], stages: List[Any], options=None):
        self.inputs = list(inputs)
        self.stages = list(stages)
        self.options = options
        self.stats: List[OpStats] = []

    def run(self) -> Iterator:
        from .streaming import StreamingScheduler

        inputs, stages = _optimize(self.inputs, self.stages)
        sched = StreamingScheduler(
            inputs, stages, self.stats, options=self.options
        )
        return sched.run_stream()


def _pushdown_rules(inputs: List[Any], stages: List[Any]):
    """Projection/predicate pushdown into pushdown-capable read tasks
    (reference ``data/_internal/logical/rules/``: projection + filter
    pushdown into ParquetDatasource).  Walks the leading marker stages:
    a predicate pushes into the read AND its stage is dropped (the scan
    is row-exact); a projection narrows the read but the stage stays (it
    is a cheap column slice and also covers non-pushdown inputs)."""
    from .datasource import ParquetReadTask

    if not stages or not inputs or not all(
        isinstance(i, ParquetReadTask) for i in inputs
    ):
        return inputs, stages
    stages = list(stages)
    out_inputs = list(inputs)
    idx = 0
    needed_cols: Optional[set] = None
    while idx < len(stages):
        st = stages[idx]
        if not isinstance(st, MapStage):
            break
        if st.predicate is not None:
            out_inputs = [t.with_predicate(st.predicate) for t in out_inputs]
            # Predicate columns must survive any projection pushed later.
            pred_cols = {c for c, _op, _v in st.predicate}
            if needed_cols is not None:
                needed_cols |= pred_cols
            stages.pop(idx)
            continue
        if st.projection is not None:
            cols = set(st.projection)
            needed_cols = cols if needed_cols is None else needed_cols | cols
            idx += 1
            continue
        break
    if needed_cols is not None:
        out_inputs = [
            t.with_projection(sorted(needed_cols)) for t in out_inputs
        ]
    return out_inputs, stages


def _elide_repartitions(inputs: List[Any], stages: List[Any]) -> List[Any]:
    """Repartition elision (reference fuse/elide-repartition rules):
    consecutive repartitions collapse to the last (the earlier exchange's
    block assignment is fully overwritten by the later one).  A repartition
    matching the current block COUNT is deliberately NOT elided — it also
    rebalances row counts across blocks."""
    out: List[Any] = []
    for stage in stages:
        is_rep = isinstance(stage, AllToAllStage) and stage.name == "Repartition"
        if (
            is_rep
            and out
            and isinstance(out[-1], AllToAllStage)
            and out[-1].name == "Repartition"
        ):
            out[-1] = stage  # last repartition wins
            continue
        out.append(stage)
    return out


def _optimize(inputs: List[Any], stages: List[Any]):
    """Plan rewriting (reference ``data/_internal/logical/rules/``):
    (0) projection/predicate pushdown into parquet reads and repartition
    elision; (1) adjacent task-compute MapStages fuse; (2) a MapStage
    directly before an AllToAllStage fuses into its map phase; (3) a
    leading non-map stage over ReadTasks gets a normalization MapStage."""
    inputs, stages = _pushdown_rules(inputs, stages)
    stages = _elide_repartitions(inputs, stages)
    fused: List[Any] = []
    for stage in stages:
        if fused and isinstance(stage, MapStage) and isinstance(fused[-1], MapStage):
            merged = fused[-1].fuse(stage)
            if merged is not None:
                fused[-1] = merged
                continue
        if (
            fused
            and hasattr(stage, "with_fused")
            and isinstance(fused[-1], MapStage)
            and fused[-1].compute is None
            and not stage.fused_transforms
        ):
            # Copy, never mutate: the stage object is shared by every
            # Dataset derived from the same plan.
            fused.append(stage.with_fused(fused.pop().transforms))
            continue
        fused.append(stage)
    needs_norm = any(isinstance(i, ReadTask) for i in inputs)
    if needs_norm and not (
        fused
        and (isinstance(fused[0], MapStage) or hasattr(fused[0], "with_fused"))
    ):
        fused.insert(0, MapStage([], ["Read"]))
    return inputs, fused
