"""TFRecord datasource — no TensorFlow dependency.

Reference: ray ``python/ray/data/datasource/tfrecords_datasource.py``
(which imports TF).  TPU-native stacks feed JAX, so this reads the format
directly: the TFRecord framing is

    [8B little-endian length][4B masked crc32c(length)]
    [data bytes]            [4B masked crc32c(data)]

and the payload is a ``tf.train.Example`` protobuf — a single map field
``features`` of name → Feature, where Feature is a oneof of bytes_list /
float_list / int64_list.  Both layers are simple enough to parse (and
write) by hand; rows come back as dicts of python/numpy values.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# ------------------------------------------------------------------ crc32c
_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE:
        return _CRC_TABLE
    poly = 0x82F63B78  # Castagnoli, reflected
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    _CRC_TABLE = table
    return table


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------ proto parsing
def _read_varint(buf: bytes, off: int):
    result = shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _write_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _iter_fields(buf: bytes) -> Iterator:
    """Yield (field_number, wire_type, value) over a proto message."""
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            value, off = _read_varint(buf, off)
        elif wire == 2:  # length-delimited
            ln, off = _read_varint(buf, off)
            value = buf[off : off + ln]
            off += ln
        elif wire == 5:  # 32-bit
            value = buf[off : off + 4]
            off += 4
        elif wire == 1:  # 64-bit
            value = buf[off : off + 8]
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def _parse_feature(buf: bytes):
    """Feature: oneof {1: BytesList, 2: FloatList, 3: Int64List}."""
    for field, _wire, value in _iter_fields(buf):
        if field == 1:  # BytesList { repeated bytes value = 1 }
            return [v for f, _w, v in _iter_fields(value) if f == 1]
        if field == 2:  # FloatList { repeated float value = 1 [packed] }
            floats: List[float] = []
            for f, w, v in _iter_fields(value):
                if f != 1:
                    continue
                if w == 2:  # packed
                    floats.extend(
                        struct.unpack(f"<{len(v) // 4}f", v)
                    )
                else:
                    floats.append(struct.unpack("<f", v)[0])
            return np.asarray(floats, np.float32)
        if field == 3:  # Int64List { repeated int64 value = 1 [packed] }
            def signed(x: int) -> int:
                # proto int64 varints are two's-complement in 64 bits.
                return x - (1 << 64) if x >= (1 << 63) else x

            ints: List[int] = []
            for f, w, v in _iter_fields(value):
                if f != 1:
                    continue
                if w == 2:
                    off = 0
                    while off < len(v):
                        x, off = _read_varint(v, off)
                        ints.append(signed(x))
                else:
                    ints.append(signed(v))
            return np.asarray(ints, np.int64)
    return None


def parse_example(data: bytes) -> Dict[str, Any]:
    """tf.train.Example { Features features = 1 };
    Features { map<string, Feature> feature = 1 }."""
    row: Dict[str, Any] = {}
    for field, _w, features_buf in _iter_fields(data):
        if field != 1:
            continue
        for f2, _w2, entry in _iter_fields(features_buf):
            if f2 != 1:
                continue
            name, feat = None, None
            for f3, _w3, v3 in _iter_fields(entry):
                if f3 == 1:
                    name = v3.decode()
                elif f3 == 2:
                    feat = _parse_feature(v3)
            if name is not None:
                value = feat
                if isinstance(value, list) and len(value) == 1:
                    value = value[0]
                elif isinstance(value, np.ndarray) and value.size == 1:
                    value = value[0]
                row[name] = value
    return row


def _encode_feature(value) -> bytes:
    """Python value → Feature bytes (bytes/str → BytesList, float(s) →
    FloatList, int(s) → Int64List)."""

    def ld(field: int, payload: bytes) -> bytes:
        return _write_varint(field << 3 | 2) + _write_varint(len(payload)) + payload

    if isinstance(value, (bytes, str)):
        b = value.encode() if isinstance(value, str) else value
        return ld(1, ld(1, b))
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.floating):
        packed = struct.pack(f"<{arr.size}f", *arr.ravel().astype(np.float32))
        return ld(2, ld(1, packed))
    if np.issubdtype(arr.dtype, np.integer):
        payload = b"".join(
            _write_varint(int(x) & ((1 << 64) - 1)) for x in arr.ravel()
        )
        return ld(3, ld(1, payload))
    raise TypeError(f"cannot encode {type(value).__name__} as a Feature")


def encode_example(row: Dict[str, Any]) -> bytes:
    def ld(field: int, payload: bytes) -> bytes:
        return _write_varint(field << 3 | 2) + _write_varint(len(payload)) + payload

    entries = b""
    for name, value in row.items():
        entry = ld(1, name.encode()) + ld(2, _encode_feature(value))
        entries += ld(1, entry)
    return ld(1, entries)


# ------------------------------------------------------------------ file IO
def read_tfrecord_file(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                break
            (length,) = struct.unpack("<Q", header[:8])
            data = f.read(length)
            f.read(4)  # data crc (not verified; format-level integrity
            # belongs to the storage layer here)
            rows.append(parse_example(data))
    return rows


def write_tfrecord_file(rows: List[Dict[str, Any]], path: str) -> str:
    with open(path, "wb") as f:
        for row in rows:
            data = encode_example(row)
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))
    return path
