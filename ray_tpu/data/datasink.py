"""Datasink: the unified write abstraction.

Reference: ray ``python/ray/data/datasource/datasink.py`` +
``data/_internal/datasource/parquet_datasink.py`` (and csv/json peers) —
every ``Dataset.write_*`` funnels through one interface: per-block write
tasks fan out on the cluster, then a single ``on_write_complete`` commit
hook runs on the driver.

Sinks keep the columnar fast path: a ``ColumnarBlock`` writes straight
from its numpy columns (parquet: zero-copy into Arrow arrays) — no row
materialization on the write path either.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

from .block import Block, ColumnarBlock


class Datasink:
    """One output format/destination.  Subclasses implement
    ``write_block`` (runs inside a worker task, must be picklable) and may
    override ``on_write_complete`` (driver-side commit)."""

    extension = ""

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        """Write one block; return metadata (at least ``path``)."""
        raise NotImplementedError

    def on_write_complete(self, results: List[Dict[str, Any]]) -> None:
        """Driver-side commit hook after every block landed (manifest
        writes, renames, metadata registration)."""

    @staticmethod
    def _rows(block: Block) -> List[dict]:
        return [r if isinstance(r, dict) else {"value": r} for r in block]

    @staticmethod
    def _key_union(rows: List[dict]) -> List[str]:
        """Ordered union of row keys (heterogeneous rows allowed)."""
        keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(k)
        return keys


class ParquetDatasink(Datasink):
    extension = ".parquet"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import pyarrow.parquet as pq

        from .arrow import block_to_arrow

        table = block_to_arrow(block)
        pq.write_table(table, path)
        return {"path": path, "rows": table.num_rows}


class CSVDatasink(Datasink):
    extension = ".csv"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import csv

        rows = self._rows(block)
        keys = self._key_union(rows)
        with open(path, "w", newline="") as f:
            if rows:
                writer = csv.DictWriter(f, fieldnames=keys, restval="")
                writer.writeheader()
                writer.writerows(rows)
        return {"path": path, "rows": len(rows)}


class JSONDatasink(Datasink):
    extension = ".jsonl"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import json

        n = 0
        with open(path, "w") as f:
            for r in block:
                f.write(json.dumps(r, default=str) + "\n")
                n += 1
        return {"path": path, "rows": n}


class NumpyDatasink(Datasink):
    """One ``.npz`` per block: columnar blocks store their columns
    verbatim; row blocks stack a ``value`` array."""

    extension = ".npz"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import numpy as np

        if isinstance(block, ColumnarBlock):
            np.savez(path, **block.columns)
            return {"path": path, "rows": len(block)}
        rows = self._rows(block)
        keys = self._key_union(rows)
        np.savez(
            path,
            **{k: np.asarray([r.get(k) for r in rows]) for k in keys},
        )
        return {"path": path, "rows": len(rows)}


class TFRecordsDatasink(Datasink):
    """tf.train.Example TFRecord files, TF-free (codec shared with the
    read path in ``data/tfrecord.py``; reference ``tfrecords_datasink.py``
    imports TensorFlow)."""

    extension = ".tfrecord"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        from .tfrecord import write_tfrecord_file

        rows = self._rows(block)
        write_tfrecord_file(rows, path)
        return {"path": path, "rows": len(rows)}


class AvroDatasink(Datasink):
    """Avro object-container files (codec in ``data/avro.py``).  Schema is
    inferred per block unless pinned at construction — pin it when blocks
    may be heterogeneous."""

    extension = ".avro"

    def __init__(self, schema: Dict[str, Any] = None, codec: str = "null"):
        self.schema = schema
        self.codec = codec

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        from .avro import write_avro_file

        rows = self._rows(block)
        write_avro_file(rows, path, schema=self.schema, codec=self.codec)
        return {"path": path, "rows": len(rows)}


class WebDatasetDatasink(Datasink):
    """One ``.tar`` shard per block (reference ``webdataset_datasink.py``).
    Rows are WebDataset samples: ``__key__`` names the sample, every other
    column becomes a tar member ``<key>.<column>``; bytes pass through,
    str utf-8-encodes, anything else JSON-encodes."""

    extension = ".tar"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import io
        import json
        import tarfile

        rows = self._rows(block)
        with tarfile.open(path, "w") as tf:
            for i, row in enumerate(rows):
                key = str(row.get("__key__", f"{i:08d}"))
                for col, value in row.items():
                    if col == "__key__":
                        continue
                    if isinstance(value, (bytes, bytearray)):
                        data = bytes(value)
                    elif isinstance(value, str):
                        data = value.encode()
                    else:
                        data = json.dumps(value, default=str).encode()
                    info = tarfile.TarInfo(f"{key}.{col}")
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))
        return {"path": path, "rows": len(rows)}


class SQLDatasink(Datasink):
    """INSERT every row into a DB-API table (reference
    ``sql_datasink.py``).  ``connection_factory`` runs inside the write
    task; one connection + one executemany per block.  ``paramstyle``
    must match the driver ("qmark" for sqlite3, "format"/"pyformat" for
    postgres/mysql drivers) — DB-API placeholders are per-module and
    undiscoverable from a connection object."""

    extension = ""  # no files — "path" is only a task label

    def __init__(self, table: str, connection_factory,
                 paramstyle: str = "qmark"):
        self.table = table
        self.factory = connection_factory
        self.paramstyle = paramstyle

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        rows = self._rows(block)
        if not rows:
            return {"path": path, "rows": 0}
        keys = self._key_union(rows)
        conn = self.factory()
        try:
            ph = {"qmark": "?", "format": "%s", "pyformat": "%s",
                  "numeric": None}.get(self.paramstyle)
            if ph is None:
                raise ValueError(
                    f"unsupported paramstyle {self.paramstyle!r} "
                    "(use qmark/format/pyformat)"
                )
            placeholders = ", ".join([ph] * len(keys))
            sql = (
                f"INSERT INTO {self.table} ({', '.join(keys)}) "
                f"VALUES ({placeholders})"
            )
            conn.cursor().executemany(
                sql, [tuple(r.get(k) for k in keys) for r in rows]
            )
            conn.commit()
        finally:
            conn.close()
        return {"path": path, "rows": len(rows)}


class ImageDatasink(Datasink):
    """One image file per row via PIL (reference ``image_datasink.py``).
    Rows carry an HxWxC uint8 array in ``column`` (default ``image``);
    filenames come from a ``path`` column's basename when present."""

    extension = ""  # writes one file per ROW; block path becomes a prefix

    def __init__(self, column: str = "image", format: str = "png"):
        self.column = column
        self.format = format

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import numpy as np
        from PIL import Image

        files = []
        seen = set()
        for i, row in enumerate(self._rows(block)):
            arr = np.asarray(row[self.column])
            if "path" in row:
                stem = os.path.splitext(os.path.basename(str(row["path"])))[0]
                if stem in seen:  # two source dirs, same basename
                    stem = f"{stem}-{i:06d}"
            else:
                stem = f"{i:06d}"
            seen.add(stem)
            out = f"{path}-{stem}.{self.format}"
            Image.fromarray(arr).save(out)
            files.append(out)
        # "path" stays the block label (the write plumbing keys on it);
        # the files actually written are their own field.
        return {"path": path, "rows": len(files), "files": files}


class ManifestedDatasink(Datasink):
    """Wrap any sink with a commit manifest: the output directory gains a
    ``_MANIFEST.json`` listing every part file, written LAST — readers
    that require the manifest never observe a partial write (the
    manifest-last commit protocol the checkpoint layer also uses)."""

    def __init__(self, inner: Datasink):
        self.inner = inner
        self.extension = inner.extension

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        return self.inner.write_block(block, path)

    def on_write_complete(self, results: List[Dict[str, Any]]) -> None:
        import json

        from .filesystem import is_uri, resolve

        self.inner.on_write_complete(results)
        if not results:
            return
        first = results[0]["path"]
        sep = "/" if is_uri(first) else os.sep
        out_dir = first.rsplit(sep, 1)[0]
        manifest = {
            "parts": [r["path"].rsplit(sep, 1)[-1] for r in results],
            # _write_block guarantees num_rows; sinks may also set rows.
            "rows": sum(
                r.get("rows", r.get("num_rows", 0)) for r in results
            ),
        }
        fs, _ = resolve(out_dir)
        # write_bytes is atomic per-file on every backend (local: tmp +
        # rename; KV: single put) — the manifest-last commit survives.
        fs.write_bytes(
            fs.join(out_dir, "_MANIFEST.json"),
            json.dumps(manifest).encode(),
        )
