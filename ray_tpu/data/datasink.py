"""Datasink: the unified write abstraction.

Reference: ray ``python/ray/data/datasource/datasink.py`` +
``data/_internal/datasource/parquet_datasink.py`` (and csv/json peers) —
every ``Dataset.write_*`` funnels through one interface: per-block write
tasks fan out on the cluster, then a single ``on_write_complete`` commit
hook runs on the driver.

Sinks keep the columnar fast path: a ``ColumnarBlock`` writes straight
from its numpy columns (parquet: zero-copy into Arrow arrays) — no row
materialization on the write path either.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

from .block import Block, ColumnarBlock


class Datasink:
    """One output format/destination.  Subclasses implement
    ``write_block`` (runs inside a worker task, must be picklable) and may
    override ``on_write_complete`` (driver-side commit)."""

    extension = ""

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        """Write one block; return metadata (at least ``path``)."""
        raise NotImplementedError

    def on_write_complete(self, results: List[Dict[str, Any]]) -> None:
        """Driver-side commit hook after every block landed (manifest
        writes, renames, metadata registration)."""

    @staticmethod
    def _rows(block: Block) -> List[dict]:
        return [r if isinstance(r, dict) else {"value": r} for r in block]

    @staticmethod
    def _key_union(rows: List[dict]) -> List[str]:
        """Ordered union of row keys (heterogeneous rows allowed)."""
        keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(k)
        return keys


class ParquetDatasink(Datasink):
    extension = ".parquet"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import pyarrow.parquet as pq

        from .arrow import block_to_arrow

        table = block_to_arrow(block)
        pq.write_table(table, path)
        return {"path": path, "rows": table.num_rows}


class CSVDatasink(Datasink):
    extension = ".csv"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import csv

        rows = self._rows(block)
        keys = self._key_union(rows)
        with open(path, "w", newline="") as f:
            if rows:
                writer = csv.DictWriter(f, fieldnames=keys, restval="")
                writer.writeheader()
                writer.writerows(rows)
        return {"path": path, "rows": len(rows)}


class JSONDatasink(Datasink):
    extension = ".jsonl"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import json

        n = 0
        with open(path, "w") as f:
            for r in block:
                f.write(json.dumps(r, default=str) + "\n")
                n += 1
        return {"path": path, "rows": n}


class NumpyDatasink(Datasink):
    """One ``.npz`` per block: columnar blocks store their columns
    verbatim; row blocks stack a ``value`` array."""

    extension = ".npz"

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        import numpy as np

        if isinstance(block, ColumnarBlock):
            np.savez(path, **block.columns)
            return {"path": path, "rows": len(block)}
        rows = self._rows(block)
        keys = self._key_union(rows)
        np.savez(
            path,
            **{k: np.asarray([r.get(k) for r in rows]) for k in keys},
        )
        return {"path": path, "rows": len(rows)}


class ManifestedDatasink(Datasink):
    """Wrap any sink with a commit manifest: the output directory gains a
    ``_MANIFEST.json`` listing every part file, written LAST — readers
    that require the manifest never observe a partial write (the
    manifest-last commit protocol the checkpoint layer also uses)."""

    def __init__(self, inner: Datasink):
        self.inner = inner
        self.extension = inner.extension

    def write_block(self, block: Block, path: str) -> Dict[str, Any]:
        return self.inner.write_block(block, path)

    def on_write_complete(self, results: List[Dict[str, Any]]) -> None:
        import json

        self.inner.on_write_complete(results)
        if not results:
            return
        out_dir = os.path.dirname(results[0]["path"])
        manifest = {
            "parts": [os.path.basename(r["path"]) for r in results],
            # _write_block guarantees num_rows; sinks may also set rows.
            "rows": sum(
                r.get("rows", r.get("num_rows", 0)) for r in results
            ),
        }
        tmp = os.path.join(out_dir, "_MANIFEST.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(out_dir, "_MANIFEST.json"))
