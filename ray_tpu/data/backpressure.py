"""Per-operator resource budgets and backpressure policies.

Reference: ray ``python/ray/data/_internal/execution/resource_manager.py:47``
(per-operator memory budgets from the shared object-store budget) and
``backpressure_policy/backpressure_policy.py:14`` (pluggable launch gates).

Here each streaming operator node consults its ``OpResourceState`` before
launching a task: the concurrency-cap policy is the round-1 behavior, and
the memory-budget policy bounds *estimated object-store bytes in flight*
(average completed output size × outstanding tasks) so a stage producing
huge blocks throttles instead of flooding /dev/shm — which matters more
here than in the reference because the node arena is a fixed-size mmap.

Under the operator-graph scheduler (``streaming.py``),
``on_output_consumed`` fires at task COMPLETION (harvest), not at
downstream consume: RUNNING tasks are the memory model's in-flight set,
while completed-but-unconsumed blocks are bounded separately by the
per-op output queue depth (``data_output_queue_depth``).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.config import GlobalConfig


class OpResourceState:
    """Live accounting for one operator (ResourceManager per-op slice)."""

    def __init__(self, name: str):
        self.name = name
        self.outstanding = 0  # launched, not yet consumed downstream
        self.completed_tasks = 0
        # The size average only counts outputs whose size was actually
        # observed — unknown-size completions must not dilute it toward 0
        # (which would disable the memory policy exactly when it matters).
        self.sized_tasks = 0
        self.completed_bytes = 0

    @property
    def avg_output_bytes(self) -> float:
        if self.sized_tasks == 0:
            return 0.0
        return self.completed_bytes / self.sized_tasks

    @property
    def estimated_inflight_bytes(self) -> float:
        return self.avg_output_bytes * self.outstanding

    def on_launch(self):
        self.outstanding += 1

    def on_output_consumed(self, nbytes: Optional[int]):
        self.outstanding -= 1
        self.completed_tasks += 1
        if nbytes:
            self.sized_tasks += 1
            self.completed_bytes += nbytes


class BackpressurePolicy:
    """Gate for launching one more task of an operator."""

    def can_launch(self, op: OpResourceState) -> bool:  # pragma: no cover
        raise NotImplementedError


class ConcurrencyCapPolicy(BackpressurePolicy):
    def __init__(self, cap: Optional[int] = None):
        self.cap = cap

    def can_launch(self, op: OpResourceState) -> bool:
        cap = self.cap if self.cap is not None else GlobalConfig.data_max_tasks_per_op
        return op.outstanding < cap


class MemoryBudgetPolicy(BackpressurePolicy):
    """Throttle when estimated in-flight output bytes exceed the op budget.
    Always admits at least one task (liveness) and only engages once an
    average output size is known."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self.budget_bytes = budget_bytes

    def can_launch(self, op: OpResourceState) -> bool:
        budget = (
            self.budget_bytes
            if self.budget_bytes is not None
            else GlobalConfig.data_memory_budget_per_op_bytes
        )
        if budget <= 0 or op.outstanding == 0 or op.avg_output_bytes == 0:
            return True
        return op.estimated_inflight_bytes + op.avg_output_bytes <= budget


def default_policies() -> List[BackpressurePolicy]:
    return [ConcurrencyCapPolicy(), MemoryBudgetPolicy()]


class ResourceManager:
    """Pipeline-level budget divider (reference
    ``resource_manager.py:47``): one shared object-store budget split
    evenly across the plan's concurrently-running operators, so a deep
    pipeline cannot claim N × the per-op default.  (The reference also
    re-reserves dynamically by op demand; the even split is its starting
    allocation and the behavior here.)"""

    def __init__(self, n_ops: int, total_bytes: Optional[int] = None):
        if total_bytes is None:
            total_bytes = GlobalConfig.data_memory_budget_total_bytes
        if total_bytes <= 0:  # derive from the node's shm arena budget
            total_bytes = int(
                GlobalConfig.object_store_memory_bytes
                * GlobalConfig.data_memory_budget_fraction
            )
        self.total_bytes = total_bytes
        self.per_op_bytes = max(1, total_bytes // max(1, n_ops))

    def policies_for_op(self) -> List[BackpressurePolicy]:
        # The explicit per-op knob stays authoritative when tighter than
        # this pipeline's even split — the shared budget only ever
        # SHRINKS an op's allowance (deep plan), never relaxes it.
        per_op = self.per_op_bytes
        knob = GlobalConfig.data_memory_budget_per_op_bytes
        if knob > 0:
            per_op = min(per_op, knob)
        return [
            ConcurrencyCapPolicy(),
            MemoryBudgetPolicy(per_op),
        ]


def can_launch(op: OpResourceState, policies: List[BackpressurePolicy]) -> bool:
    return all(p.can_launch(op) for p in policies)


def ref_size_if_known(ref) -> Optional[int]:
    """Owner-side size of a completed object (no data fetch)."""
    try:
        worker = ref._worker
        obj = worker.owned.get(ref.id)
        return obj.size if obj is not None else None
    except Exception:
        return None
