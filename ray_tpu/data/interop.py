"""Ecosystem interop: pandas / torch / HuggingFace datasets ⇄ Dataset.

Reference: ray ``python/ray/data/read_api.py`` ``from_pandas`` /
``from_torch`` / ``from_huggingface`` and ``Dataset.to_pandas``.  All
three bridge through the columnar block (numpy columns), so numeric data
round-trips without per-row materialization; the HuggingFace path rides
the existing Arrow zero-copy bridge (HF datasets are Arrow-backed).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Union

if TYPE_CHECKING:  # pragma: no cover
    import pandas as pd


def from_pandas(dfs: Union["pd.DataFrame", List["pd.DataFrame"]],
                parallelism: int = 8):
    """DataFrame(s) → Dataset of ColumnarBlocks.  Numeric columns wrap the
    frame's numpy arrays directly; a single frame is split into up to
    ``parallelism`` blocks so downstream transforms parallelize."""
    import pandas as pd

    from .block import ColumnarBlock
    from .dataset import from_blocks

    if isinstance(dfs, pd.DataFrame):
        n = len(dfs)
        k = max(1, min(parallelism, n or 1))
        size = (n + k - 1) // k
        dfs = [dfs.iloc[i * size:(i + 1) * size] for i in range(k)
               if i * size < n] or [dfs]
    blocks = []
    for df in dfs:
        cols = {}
        for name in df.columns:
            series = df[name]
            arr = series.to_numpy()
            cols[str(name)] = arr
        blocks.append(ColumnarBlock(cols))
    return from_blocks(blocks)


def dataset_to_pandas(ds) -> "pd.DataFrame":
    """Materialize a Dataset as ONE DataFrame (via the Arrow bridge, so
    primitive columns move zero-copy Block→Table→frame)."""
    from .arrow import dataset_to_arrow

    return dataset_to_arrow(ds).to_pandas()


def from_torch(torch_dataset, parallelism: int = 8):
    """Map-style ``torch.utils.data.Dataset`` → Dataset (reference
    ``torch_datasource.py``).  Index ranges shard across read tasks; the
    torch dataset itself is pickled to each task, so items load inside
    workers, not on the driver.  Items become ``{"item": x}`` rows
    (tensors convert to numpy); iterable-style datasets materialize in
    one task since they can't be index-sharded."""
    from .dataset import read_datasource
    from .datasource import Datasource, ReadTask

    class _TorchDatasource(Datasource):
        def get_read_tasks(self, k):
            def fetch(lo, hi):
                out = []
                for i in range(lo, hi):
                    out.append({"item": _to_numpy(torch_dataset[i])})
                return out

            try:
                n = len(torch_dataset)
            except TypeError:
                # Iterable-style: single sequential pass.
                return [ReadTask(
                    lambda: [{"item": _to_numpy(x)} for x in torch_dataset],
                    {},
                )]
            k = max(1, min(k, n or 1))
            size = (n + k - 1) // k
            return [
                ReadTask(lambda a=i * size, b=min((i + 1) * size, n):
                         fetch(a, b), {"num_rows": min((i + 1) * size, n) - i * size})
                for i in range(k) if i * size < n
            ]

    return read_datasource(_TorchDatasource(), parallelism)


def _to_numpy(x):
    try:
        import torch

        if isinstance(x, torch.Tensor):
            return x.detach().cpu().numpy()
        if isinstance(x, (tuple, list)):
            return type(x)(_to_numpy(v) for v in x)
        if isinstance(x, dict):
            return {k: _to_numpy(v) for k, v in x.items()}
    except ImportError:  # pragma: no cover
        pass
    return x


def from_huggingface(hf_dataset, parallelism: int = 8):
    """HuggingFace ``datasets.Dataset`` → Dataset via its Arrow table
    (reference ``huggingface_datasource.py``).  Zero-copy for primitive
    columns; the table is sliced into up to ``parallelism`` blocks."""
    from .arrow import arrow_to_block
    from .dataset import from_blocks

    table = getattr(hf_dataset.data, "table", None)
    if table is None:  # pragma: no cover — older datasets versions
        table = hf_dataset.data
    table = table.combine_chunks()
    n = table.num_rows
    k = max(1, min(parallelism, n or 1))
    size = (n + k - 1) // k
    blocks = [
        arrow_to_block(table.slice(i * size, size))
        for i in range(k) if i * size < n
    ] or [arrow_to_block(table)]
    return from_blocks(blocks)
