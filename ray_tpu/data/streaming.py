"""Streaming data-plane scheduler: an operator-graph executor.

Replaces the iterator-chained executor (each stage a generator pulling its
upstream, every block funneled through head-of-line ``popleft``) with a
real scheduler over operator NODES connected by bounded input/output
queues (reference: ray ``python/ray/data/_internal/execution/
streaming_executor_state.py`` — topology + ``select_operator_to_run``;
Podracer's producer/consumer decoupling is the design argument: deep
asynchronous pipelines keep accelerators fed).

Three capabilities over the old chain:

  - **out-of-order streaming** — completions are harvested with
    ``ray_tpu.wait(..., num_returns=1)`` over the whole in-flight set, so
    one straggler map task no longer blocks finished downstream work.
    Ordered emission stays the DEFAULT (``iter_batches`` determinism);
    unordered is opt-in via ``ExecutionOptions(preserve_order=False)``,
    which emits each block the moment its task finishes.
  - **operator autoscaling** — ``ActorPoolStrategy(min_size, max_size)``
    pools grow on sustained input-queue pressure, shrink (idle actors are
    killed) on starvation, and dispatch least-loaded instead of blind
    round-robin.
  - **dynamic block shaping** — map outputs larger than
    ``target_block_size_bytes`` are split and undersized runs coalesced
    before the next exchange, bounding per-task memory and shuffle fan-in
    skew (reference: ray's dynamic block splitting /
    ``OutputBlockSizeOption``).

The scheduler also owns **early-exit cancellation**: when a consumer stops
pulling (``take(n)`` satisfied, ``limit`` reached, or the iterator is
abandoned), every still-in-flight upstream task ref is ``ray_tpu.cancel``ed
and actor pools are torn down instead of running to completion.

Everything is driven from the consuming thread — one ``_step`` pass feeds
sources, launches under the ``ResourceManager`` budget, harvests
completions, and autoscales; blocking waits are bounded slices
(``data_straggler_wait_slice_s``) and recorded as straggler time.  The
scheduler self-instruments via the flight recorder: queue depths,
straggler waits, autoscale events, split/coalesce counts.
"""

from __future__ import annotations

import logging
import math
import re
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional

import ray_tpu

from ..core.config import GlobalConfig
from ..util import flight_recorder as fr
# Module-level reference (not from-imports) for the accounting class:
# tests monkeypatch ``backpressure.OpResourceState`` to observe launches.
from . import backpressure as _bp
from .backpressure import (
    ResourceManager,
    can_launch,
    default_policies,
    ref_size_if_known,
)
from .block import concat_columnar
from .execution import (
    LimitStage,
    MapStage,
    OpStats,
    _MapWorker,
    _run_item,
)

logger = logging.getLogger(__name__)

# Cap on how many pieces one oversized block splits into: a grossly
# mis-sized block (or tiny target) must not explode into thousands of
# near-empty objects.
_MAX_SPLIT_FANOUT = 64

# Limit-node row counting is hybrid.  At or under this size the block is
# fetched with a driver-side get: served from shm, cheaper than a remote
# counting task that queues behind in-flight upstream work on busy
# workers (measured: seconds of lease/pipeline wait on a saturated
# node), and it warms the driver's object cache for the consumer, which
# fetches this very block next.  Above it, a remote count/trim task runs
# next to the data instead — the limit must never haul hundreds of MB
# over the wire just to learn a row count.
_LIMIT_DRIVER_FETCH_MAX_BYTES = 4 << 20


def _op_label(name: str) -> str:
    """Metric-label form of an operator name: the base name only.

    Stage names embed user content — ``Filter[('v', '>=', 10)]``,
    ``Limit[5]`` — which is both unbounded label cardinality and full of
    characters (quotes, commas) that strict exposition parsers reject
    inside label values.  ``Filter[...]`` → ``Filter``."""
    m = re.match(r"\w+", name)
    return m.group(0) if m else "op"

# Data block tasks are coarse-grained (10s-100s of ms): push them depth-1
# per worker.  Under the default deep pipelining
# (max_tasks_in_flight_per_worker) a straggler pushed ahead of fast tasks
# on a shared worker serializes them at the worker's exec pipeline —
# worker-level head-of-line blocking that no amount of out-of-order
# completion harvesting can undo.
_run_block = _run_item.options(pipeline_depth=1)


def _run_block_ref(item):
    return _run_block.remote(item, [])


def _try_cancel(refs, stats: Optional[OpStats] = None) -> None:
    """Best-effort early-exit cancel that tolerates a torn-down runtime.

    Abandoned iterators are cancelled from generator ``close()``, which
    can run at GC time AFTER ``ray_tpu.shutdown()`` — ``global_worker()``
    then raises, and an exception escaping ``close()`` turns into
    "Exception ignored in" noise (or propagates to an explicit closer).
    Cancelling an already-completed ref is a documented no-op, so callers
    pass whole queues without filtering."""
    if not refs:
        return
    try:
        ray_tpu.cancel(list(refs))
    except Exception as e:  # noqa: BLE001 — teardown must not raise
        logger.debug("early-exit cancel skipped: %s", e)
        return
    if stats is not None:
        # Requests, not kills: cancel is best-effort and an
        # already-executing task runs to completion.
        stats.tasks_cancel_requested += len(refs)


@dataclass
class ExecutionOptions:
    """Per-plan execution knobs (reference: ray ``ExecutionOptions``).

    ``preserve_order=True`` (default) keeps block emission in plan order —
    ``take``/``iter_batches`` stay deterministic.  ``False`` opts into
    out-of-order streaming: blocks flow downstream the moment their task
    completes, so a straggler never head-of-line-blocks the pipeline.

    ``target_block_size_bytes`` overrides the
    ``data_target_block_size_bytes`` config knob for this plan; ``None``
    defers to the knob, ``0`` disables dynamic block shaping.
    """

    preserve_order: bool = True
    target_block_size_bytes: Optional[int] = None

    def resolved_target_block_bytes(self) -> int:
        if self.target_block_size_bytes is None:
            return GlobalConfig.data_target_block_size_bytes
        return int(self.target_block_size_bytes)


# ---------------------------------------------------------- block shaping
@ray_tpu.remote
def _split_block(block, k: int):
    """Split one block into k contiguous row ranges (num_returns=k fans
    the list into one object per part).  Row-exact: concatenating the
    parts in order reproduces the input."""
    n = len(block)
    bounds = [round(i * n / k) for i in range(k + 1)]
    parts = [block[bounds[i]:bounds[i + 1]] for i in range(k)]
    return parts if k > 1 else parts[0]


@ray_tpu.remote
def _count_rows(block) -> int:
    return len(block)


@ray_tpu.remote
def _trim_block(block, n: int):
    return block[:n]


@ray_tpu.remote
def _coalesce_blocks(*parts):
    """Concatenate small blocks into one (columnar stays columnar)."""
    cat = concat_columnar(parts)
    if cat is not None:
        return cat
    rows: list = []
    for p in parts:
        rows.extend(p)
    return rows


# ---------------------------------------------------------------- op nodes
class _OpNode:
    """One operator in the topology: a bounded input queue, an output
    queue, and (for task-running nodes) an in-flight set the scheduler
    harvests completions from."""

    def __init__(self, name: str, stats: Optional[OpStats]):
        self.name = name
        self.op_label = _op_label(name)
        self.stats = stats
        self.input: deque = deque()  # (item, enqueue_ts)
        self.out: deque = deque()
        self.input_done = False
        self.finished = False
        self._t0: Optional[float] = None
        self._input_bound = max(
            2, GlobalConfig.data_max_tasks_per_op * 2
        )
        self._out_bound = max(1, GlobalConfig.data_output_queue_depth)
        self._last_gauge = 0.0

    # -- queue plumbing (called by the scheduler) -------------------------
    def can_accept(self) -> bool:
        return len(self.input) < self._input_bound

    def add_input(self, item) -> None:
        self.input.append((item, time.perf_counter()))

    def mark_input_done(self) -> None:
        self.input_done = True

    @property
    def done(self) -> bool:
        return self.finished and not self.out

    # -- scheduling hooks --------------------------------------------------
    def inflight_refs(self):
        return ()

    def on_ready(self, ref) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    def step(self, sched: "StreamingScheduler") -> bool:
        raise NotImplementedError

    def cancel_remaining(self, sched: "StreamingScheduler") -> None:
        """Early exit: drop queued work, cancel in-flight tasks, finish.

        Queued input items and buffered output refs may themselves be
        still-pending upstream tasks (a barrier emits reduce refs before
        they finish, a shape node emits split/coalesce refs at launch) —
        cancel them too; completed refs make it a no-op."""
        _try_cancel(
            [item for item, _enq in self.input
             if isinstance(item, ray_tpu.ObjectRef)]
            + [r for r in self.out if isinstance(r, ray_tpu.ObjectRef)]
        )
        self.input.clear()
        self.out.clear()
        self.input_done = True
        self._finish()

    # -- shared helpers ----------------------------------------------------
    def _emit(self, ref) -> None:
        self.out.append(ref)
        if self.stats is not None:
            self.stats.blocks_emitted += 1

    def _mark_started(self) -> None:
        if self._t0 is None:
            self._t0 = time.perf_counter()

    def _finish(self) -> None:
        if self.finished:
            return
        self.finished = True
        if self.stats is not None:
            if self._t0 is not None:
                self.stats.wall_s = time.perf_counter() - self._t0
            fr.counter(
                fr.DATA_BLOCKS_EMITTED_TOTAL,
                float(self.stats.blocks_emitted),
                {"op": self.op_label},
            )

    def _gauge_queues(self) -> None:
        now = time.perf_counter()
        if now - self._last_gauge < GlobalConfig.data_autoscale_interval_s:
            return
        self._last_gauge = now
        fr.gauge(fr.DATA_QUEUE_DEPTH, float(len(self.input)),
                 {"op": self.op_label})


class _MapTaskNode(_OpNode):
    """Fused narrow transforms on task compute, with out-of-order
    completion harvesting and ordered/unordered emission."""

    def __init__(self, stage: MapStage, options: ExecutionOptions,
                 rm: Optional[ResourceManager], stats_list: List[OpStats]):
        st = OpStats(stage.name)
        stats_list.append(st)
        super().__init__(stage.name, st)
        self.transforms = list(stage.transforms)
        self.ordered = options.preserve_order
        self.policies = (
            rm.policies_for_op() if rm is not None else default_policies()
        )
        self.op_state = _bp.OpResourceState(stage.name)
        self._inflight: Dict[Any, int] = {}  # ref -> launch seq
        self._completed: Dict[int, Any] = {}  # ordered-mode reorder buffer
        self._launch_seq = 0
        self._emit_seq = 0

    def _buffered_out(self) -> int:
        return len(self.out) + len(self._completed)

    def inflight_refs(self):
        return self._inflight.keys()

    def step(self, sched) -> bool:
        if self.finished:
            return False
        progress = False
        while (
            self.input
            and self._buffered_out() < self._out_bound
            and can_launch(self.op_state, self.policies)
        ):
            item, enq = self.input.popleft()
            self._mark_started()
            self.stats.add_queue_wait(time.perf_counter() - enq)
            ref = _run_block.remote(item, self.transforms)
            self._inflight[ref] = self._launch_seq
            self._launch_seq += 1
            self.op_state.on_launch()
            self.stats.num_tasks += 1
            progress = True
        self._gauge_queues()
        return self._maybe_finish() or progress

    def on_ready(self, ref) -> None:
        seq = self._inflight.pop(ref, None)
        if seq is None:
            return
        self.op_state.on_output_consumed(ref_size_if_known(ref))
        if self.ordered:
            self._completed[seq] = ref
            while self._emit_seq in self._completed:
                self._emit(self._completed.pop(self._emit_seq))
                self._emit_seq += 1
        else:
            self._emit(ref)
        self._maybe_finish()

    def _maybe_finish(self) -> bool:
        if (
            not self.finished
            and self.input_done
            and not self.input
            and not self._inflight
            and not self._completed
        ):
            self._finish()
            return True
        return False

    def cancel_remaining(self, sched) -> None:
        _try_cancel(list(self._inflight), self.stats)
        self._inflight.clear()
        self._completed.clear()
        super().cancel_remaining(sched)


class _PoolActor:
    __slots__ = ("handle", "inflight", "idle_since")

    def __init__(self, handle):
        self.handle = handle
        self.inflight = 0
        self.idle_since = time.perf_counter()


class _ActorPoolNode(_OpNode):
    """Stateful map on an autoscaling actor pool: least-loaded dispatch,
    scale-up on sustained input-queue pressure, scale-down (kill idle
    actors) on starvation."""

    def __init__(self, stage: MapStage, options: ExecutionOptions,
                 stats_list: List[OpStats]):
        st = OpStats(stage.name)
        stats_list.append(st)
        super().__init__(stage.name, st)
        strat = stage.compute
        self.transforms = list(stage.transforms)
        self.ordered = options.preserve_order
        self.min_size = strat.min_size
        self.max_size = strat.max_size
        self.max_in_flight = strat.max_tasks_in_flight_per_actor
        self._worker_cls = ray_tpu.remote(_MapWorker).options(
            num_cpus=strat.num_cpus if strat.num_cpus is not None else 1,
            num_tpus=strat.num_tpus or None,
        )
        self._actors: List[_PoolActor] = []
        self._inflight: Dict[Any, tuple] = {}  # ref -> (seq, _PoolActor)
        self._completed: Dict[int, Any] = {}
        self._launch_seq = 0
        self._emit_seq = 0
        self._last_autoscale = 0.0
        self._pressure_streak = 0
        self._force_scale_up = False
        self._input_bound = max(
            self._input_bound, self.max_size * self.max_in_flight * 2
        )
        for _ in range(self.min_size):
            self._spawn_actor()
        self._record_pool_size()
        # SLO remediation hook: while this pool runs, a sustained
        # queue_pressure finding on its op can force one scale-up
        # (outside the two-streak hysteresis; still bounded by max_size).
        from ray_tpu.util import remediation as _remediation

        self._remediation_handle = _remediation.register_actuator(
            "data_pool_scale_up", self._remediation_scale_up,
            target=self.op_label,
        )

    def _remediation_scale_up(self, target: str, violation, **_kw) -> str:
        from ray_tpu.util.remediation import RemediationSkipped

        if self.finished:
            raise RemediationSkipped("pool already finished")
        if len(self._actors) >= self.max_size:
            raise RemediationSkipped(f"at max_size={self.max_size}")
        self._force_scale_up = True  # applied by the scheduler thread
        return f"pool {self.op_label}: scale-up forced"

    # -- pool management ---------------------------------------------------
    def _spawn_actor(self) -> None:
        self._actors.append(_PoolActor(self._worker_cls.remote(self.transforms)))

    def _kill_actor(self, entry: _PoolActor) -> None:
        self._actors.remove(entry)
        try:
            ray_tpu.kill(entry.handle)
        except Exception as e:  # noqa: BLE001 — teardown must not raise
            logger.debug("actor-pool kill failed: %s", e)

    def _record_pool_size(self) -> None:
        # TARGET size: handles held.  _spawn_actor's creation is async, so
        # the gauge (and timeline) lead the set of actually-running actors
        # by however long placement takes — documented in observability.md.
        n = len(self._actors)
        self.stats.pool_size = n
        self.stats.pool_size_peak = max(self.stats.pool_size_peak, n)
        self.stats.pool_size_timeline.append(n)
        fr.gauge(fr.DATA_POOL_SIZE, float(n), {"op": self.op_label})

    def _autoscale(self, now: float) -> None:
        if self._force_scale_up:
            # Remediation override: skip the streak hysteresis (the SLO
            # rule already judged the pressure sustained), keep the cap.
            self._force_scale_up = False
            if len(self._actors) < self.max_size:
                self._spawn_actor()
                self.stats.autoscale_up_events += 1
                fr.counter(fr.DATA_AUTOSCALE_EVENTS_TOTAL, 1.0,
                           {"op": self.op_label, "direction": "up"})
                self._record_pool_size()
        if now - self._last_autoscale < GlobalConfig.data_autoscale_interval_s:
            return
        self._last_autoscale = now
        saturated = self._actors and all(
            a.inflight >= self.max_in_flight for a in self._actors
        )
        if self.input and saturated and len(self._actors) < self.max_size:
            # Sustained pressure: two consecutive saturated checks, so one
            # momentary burst doesn't pay an actor launch.
            self._pressure_streak += 1
            if self._pressure_streak >= 2:
                self._pressure_streak = 0
                self._spawn_actor()
                self.stats.autoscale_up_events += 1
                fr.counter(fr.DATA_AUTOSCALE_EVENTS_TOTAL, 1.0,
                           {"op": self.op_label, "direction": "up"})
                self._record_pool_size()
        else:
            self._pressure_streak = 0
        if not self.input and len(self._actors) > self.min_size:
            idle_s = GlobalConfig.data_autoscale_idle_s
            for entry in [a for a in self._actors if a.inflight == 0]:
                if len(self._actors) <= self.min_size:
                    break
                if now - entry.idle_since >= idle_s:
                    self._kill_actor(entry)
                    self.stats.autoscale_down_events += 1
                    fr.counter(fr.DATA_AUTOSCALE_EVENTS_TOTAL, 1.0,
                               {"op": self.op_label, "direction": "down"})
                    self._record_pool_size()

    # -- scheduling --------------------------------------------------------
    def _buffered_out(self) -> int:
        return len(self.out) + len(self._completed)

    def inflight_refs(self):
        return self._inflight.keys()

    def step(self, sched) -> bool:
        if self.finished:
            return False
        progress = False
        now = time.perf_counter()
        while self.input and self._buffered_out() < self._out_bound:
            # Least-loaded dispatch (the old path striped round-robin and
            # could pile work behind one slow actor).
            entry = min(self._actors, key=lambda a: a.inflight, default=None)
            if entry is None or entry.inflight >= self.max_in_flight:
                break
            item, enq = self.input.popleft()
            self._mark_started()
            self.stats.add_queue_wait(time.perf_counter() - enq)
            ref = entry.handle.apply.remote(item)
            entry.inflight += 1
            self._inflight[ref] = (self._launch_seq, entry)
            self._launch_seq += 1
            self.stats.num_tasks += 1
            progress = True
        self._autoscale(now)
        self._gauge_queues()
        return self._maybe_finish() or progress

    def on_ready(self, ref) -> None:
        entry_seq = self._inflight.pop(ref, None)
        if entry_seq is None:
            return
        seq, entry = entry_seq
        entry.inflight -= 1
        if entry.inflight == 0:
            entry.idle_since = time.perf_counter()
        if self.ordered:
            self._completed[seq] = ref
            while self._emit_seq in self._completed:
                self._emit(self._completed.pop(self._emit_seq))
                self._emit_seq += 1
        else:
            self._emit(ref)
        self._maybe_finish()

    def _maybe_finish(self) -> bool:
        if (
            not self.finished
            and self.input_done
            and not self.input
            and not self._inflight
            and not self._completed
        ):
            self._teardown_pool()
            self._finish()
            return True
        return False

    def _teardown_pool(self) -> None:
        from ray_tpu.util import remediation as _remediation

        _remediation.unregister_actuator(self._remediation_handle)
        for entry in list(self._actors):
            self._kill_actor(entry)
        self._record_pool_size()

    def cancel_remaining(self, sched) -> None:
        # Actor-task refs are not cancellable (only normal tasks are);
        # killing the pool aborts their execution instead.
        self._inflight.clear()
        self._completed.clear()
        self._teardown_pool()
        super().cancel_remaining(sched)


class _ShapeNode(_OpNode):
    """Dynamic block shaping before an exchange: split oversized map
    outputs, coalesce undersized runs — bounds per-task memory and
    shuffle fan-in skew.  Sizes come from the owner-side object records
    (no data fetch), so a block is shaped only once its task completed."""

    def __init__(self, target_bytes: int, options: ExecutionOptions,
                 stats_list: List[OpStats]):
        st = OpStats("ShapeBlocks")
        stats_list.append(st)
        super().__init__("ShapeBlocks", st)
        self.target = int(target_bytes)
        self.ordered = options.preserve_order
        self._pending: deque = deque()  # refs in input order
        self._ready: set = set()
        self._run: List[Any] = []  # undersized coalesce buffer
        self._run_bytes = 0

    def inflight_refs(self):
        return [r for r in self._pending if r not in self._ready]

    def step(self, sched) -> bool:
        progress = False
        while self.input:
            item, _enq = self.input.popleft()
            self._mark_started()
            ref = (
                item
                if isinstance(item, ray_tpu.ObjectRef)
                else _run_block_ref(item)
            )
            self._pending.append(ref)
            progress = True
        progress |= self._drain()
        self._gauge_queues()
        return self._maybe_finish() or progress

    def on_ready(self, ref) -> None:
        self._ready.add(ref)
        self._drain()
        self._maybe_finish()

    def _drain(self) -> bool:
        progress = False
        if self.ordered:
            # Strict input order: only the head may be shaped, so the
            # emitted sequence is a deterministic function of the plan.
            while self._pending and self._pending[0] in self._ready:
                ref = self._pending.popleft()
                self._ready.discard(ref)
                self._process(ref)
                progress = True
        else:
            for ref in [r for r in self._pending if r in self._ready]:
                self._pending.remove(ref)
                self._ready.discard(ref)
                self._process(ref)
                progress = True
        return progress

    def _process(self, ref) -> None:
        size = ref_size_if_known(ref)
        if size is None or size == 0:
            self._flush_run()
            self._emit(ref)
            return
        if size > self.target:
            self._flush_run()
            k = min(int(math.ceil(size / self.target)), _MAX_SPLIT_FANOUT)
            if k <= 1:
                self._emit(ref)
                return
            refs = _split_block.options(num_returns=k).remote(ref, k)
            self.stats.num_tasks += 1
            self.stats.blocks_split += 1
            fr.counter(fr.DATA_BLOCKS_SPLIT_TOTAL, 1.0)
            for r in refs:
                self._emit(r)
            return
        if size < self.target // 2:
            self._run.append(ref)
            self._run_bytes += size
            if self._run_bytes >= self.target:
                self._flush_run()
            return
        self._flush_run()
        self._emit(ref)

    def _flush_run(self) -> None:
        if not self._run:
            return
        run, self._run = self._run, []
        self._run_bytes = 0
        if len(run) == 1:
            self._emit(run[0])
            return
        ref = _coalesce_blocks.remote(*run)
        self.stats.num_tasks += 1
        self.stats.blocks_coalesced += len(run)
        fr.counter(fr.DATA_BLOCKS_COALESCED_TOTAL, float(len(run)))
        self._emit(ref)

    def _maybe_finish(self) -> bool:
        if (
            not self.finished
            and self.input_done
            and not self.input
            and not self._pending
        ):
            self._flush_run()
            self._finish()
            return True
        return False

    def cancel_remaining(self, sched) -> None:
        _try_cancel(
            [r for r in self._pending if r not in self._ready], self.stats
        )
        self._pending.clear()
        self._ready.clear()
        self._run.clear()
        super().cancel_remaining(sched)


class _LimitNode(_OpNode):
    """Global row limit.  Signals the scheduler the moment it is
    satisfied so still-in-flight upstream work is cancelled (early-exit),
    not merely no longer launched.

    Never blocks the scheduler loop: input refs (and the remote count
    tasks of the hybrid path) sit in the shared in-flight set and are
    harvested like any other completion — a pending head (e.g. a
    straggler reduce ref out of a barrier) parks only this node, while
    other operators keep launching, harvesting, and autoscaling.  Blocks
    are consumed strictly in input order, so ``limit`` stays a
    deterministic prefix in ordered mode."""

    def __init__(self, stage: LimitStage, stats_list: List[OpStats]):
        st = OpStats(stage.name)
        stats_list.append(st)
        super().__init__(stage.name, st)
        self.remaining = stage.n
        self.satisfied = False
        self._pending: deque = deque()  # block refs in input order
        self._ready: set = set()
        self._counts: Dict[Any, Any] = {}  # block ref -> count-task ref

    def inflight_refs(self):
        refs = [r for r in self._pending if r not in self._ready]
        refs.extend(c for c in self._counts.values() if c not in self._ready)
        return refs

    def on_ready(self, ref) -> None:
        self._ready.add(ref)

    def step(self, sched) -> bool:
        if self.finished:
            return False
        progress = False
        while self.input and not self.satisfied:
            item, enq = self.input.popleft()
            self._mark_started()
            self.stats.add_queue_wait(time.perf_counter() - enq)
            self._pending.append(
                item
                if isinstance(item, ray_tpu.ObjectRef)
                else _run_block_ref(item)
            )
            progress = True
        while self._pending and not self.satisfied:
            head = self._pending[0]
            if head not in self._ready:
                break
            # Hybrid counting (see _LIMIT_DRIVER_FETCH_MAX_BYTES): the
            # block is complete, so size it from the owner-side record to
            # pick driver get (small, shm-local) vs. remote count/trim.
            size = ref_size_if_known(head)
            if size is not None and size > _LIMIT_DRIVER_FETCH_MAX_BYTES:
                cnt = self._counts.get(head)
                if cnt is None:
                    self._counts[head] = _count_rows.remote(head)
                    break  # count in flight: harvested like any ref
                if cnt not in self._ready:
                    break
                del self._counts[head]
                self._ready.discard(cnt)
                n_rows, block = ray_tpu.get(cnt, timeout=600), None
            else:
                block = ray_tpu.get(head, timeout=600)
                n_rows = len(block)
            self._pending.popleft()
            self._ready.discard(head)
            self.stats.num_tasks += 1
            progress = True
            if n_rows <= self.remaining:
                self.remaining -= n_rows
                self._emit(head)
            elif block is not None:
                self._emit(ray_tpu.put(block[: self.remaining]))
                self.remaining = 0
            else:
                self._emit(_trim_block.remote(head, self.remaining))
                self.remaining = 0
            if self.remaining <= 0:
                self.satisfied = True
                sched.on_limit_satisfied(self)
        return self._maybe_finish() or progress

    def _discard_pending(self) -> None:
        _try_cancel(
            [r for r in self._pending if r not in self._ready]
            + [c for c in self._counts.values() if c not in self._ready],
            self.stats,
        )
        self._pending.clear()
        self._ready.clear()
        self._counts.clear()

    def _maybe_finish(self) -> bool:
        if not self.finished and (
            self.satisfied
            or (self.input_done and not self.input and not self._pending)
        ):
            self.input.clear()
            self._discard_pending()
            self._finish()
            return True
        return False

    def cancel_remaining(self, sched) -> None:
        self._discard_pending()
        super().cancel_remaining(sched)


class _BarrierNode(_OpNode):
    """Internal-barrier stage (AllToAllStage / JoinStage / any plan node
    with ``.run``): absorbs its whole input, then launches the exchange
    and emits every output ref at once.  The stage's own generator
    appends its OpStats entry, so this node carries none."""

    def __init__(self, stage):
        super().__init__(stage.name, None)
        self.stage = stage
        self._collected: List[Any] = []
        self._ran = False

    def can_accept(self) -> bool:
        return True  # a barrier absorbs everything

    def step(self, sched) -> bool:
        progress = False
        while self.input:
            item, _enq = self.input.popleft()
            self._mark_started()
            self._collected.append(item)
            progress = True
        if self.input_done and not self._ran:
            self._ran = True
            # The stage generator launches the whole exchange as it is
            # drained; outputs are refs to not-yet-finished reduce tasks,
            # which downstream nodes harvest like any other completion.
            # Output refs are NOT retained here once propagated: pinning
            # every reduce output for the scheduler's lifetime would defeat
            # streaming memory release on large shuffles (the arena fills
            # while the consumer has long dropped the blocks).  Refs still
            # in self.out are cancelled by the base cancel_remaining;
            # refs already handed downstream are that node's to cancel.
            for ref in self.stage.run(iter(self._collected), sched.stats):
                self.out.append(ref)
            self._collected = []
            self.finished = True
            progress = True
        return progress

    def cancel_remaining(self, sched) -> None:
        self._collected = []
        super().cancel_remaining(sched)


# --------------------------------------------------------------- scheduler
class StreamingScheduler:
    """Drives the optimized plan's stages as an operator graph.

    One ``_step`` pass: (1) propagate blocks between queues (source →
    first node, each node's output → next node's input, bounded by
    ``can_accept``), (2) let every node launch under its backpressure
    policies, (3) harvest completed tasks across ALL nodes' in-flight
    sets — non-blocking when the pass made progress, a bounded blocking
    wait (recorded as straggler time) when it did not.
    """

    def __init__(self, inputs: List[Any], stages: List[Any],
                 stats: List[OpStats],
                 options: Optional[ExecutionOptions] = None):
        self.options = options or ExecutionOptions()
        self.stats = stats
        self.source: deque = deque(inputs)
        self.nodes: List[_OpNode] = []
        self._shut = False
        rm = (
            ResourceManager(n_ops=max(1, len(stages))) if stages else None
        )
        target = self.options.resolved_target_block_bytes()
        for stage in stages:
            if isinstance(stage, MapStage):
                if stage.compute is None:
                    node = _MapTaskNode(stage, self.options, rm, stats)
                else:
                    node = _ActorPoolNode(stage, self.options, stats)
            elif isinstance(stage, LimitStage):
                node = _LimitNode(stage, stats)
            else:  # barrier stage (exchange / join)
                if target > 0:
                    self.nodes.append(
                        _ShapeNode(target, self.options, stats)
                    )
                node = _BarrierNode(stage)
            self.nodes.append(node)

    # -- consumer-facing stream -------------------------------------------
    def run_stream(self) -> Iterator:
        if not self.nodes:
            # Plan with no stages (pre-materialized refs / raw blocks).
            yield from list(self.source)
            return
        from ray_tpu.util import tracing

        # Root the stream's whole task fan-out in one trace so a dataset
        # execution exports as a single stitched cluster trace.  Detached
        # (not installed in the current context): a start_span block
        # entered here would leak its contextvar into the consumer between
        # yields.  A consumer that already has an active span wins — the
        # launches inherit it naturally and no extra root is made.
        root = None
        if GlobalConfig.enable_task_events and tracing.current_context() is None:
            root = tracing.detached_span(
                "data.stream",
                {"ops": ",".join(n.name for n in self.nodes)},
            )
        sink = self.nodes[-1]
        try:
            while True:
                while sink.out:
                    yield sink.out.popleft()
                if all(n.done for n in self.nodes):
                    break
                with tracing.span_context(root):
                    self._step()
        finally:
            if root is not None:
                tracing.finish_span(root)
            # Normal exhaustion: everything below is a no-op.  Abandoned
            # consumer (take() satisfied, generator closed): cancel all
            # remaining upstream work and tear down pools.
            self.shutdown()

    def _step(self) -> None:
        progress = self._propagate()
        for node in self.nodes:
            progress = node.step(self) or progress
        # A productive pass polls completions; an idle one parks on the
        # in-flight set in bounded slices so stragglers don't spin the
        # scheduler thread.
        harvested = self._harvest(
            may_block=not progress and not self.nodes[-1].out
        )
        if not progress and not harvested and not self.nodes[-1].out:
            if not any(True for n in self.nodes for _ in n.inflight_refs()) \
                    and not all(n.done for n in self.nodes):
                # No queued work, nothing in flight, not done: a wiring
                # bug.  Fail loudly — a silent busy-loop or hang would be
                # strictly worse.
                raise RuntimeError(
                    "streaming scheduler stalled: "
                    + "; ".join(
                        f"{n.name}(in={len(n.input)}, out={len(n.out)}, "
                        f"done={n.done})"
                        for n in self.nodes
                    )
                )

    def _propagate(self) -> bool:
        progress = False
        first = self.nodes[0]
        while self.source and first.can_accept():
            first.add_input(self.source.popleft())
            progress = True
        if not self.source and not first.input_done:
            first.mark_input_done()
            progress = True
        for up, down in zip(self.nodes, self.nodes[1:]):
            while up.out and down.can_accept():
                down.add_input(up.out.popleft())
                progress = True
            if up.done and not down.input_done:
                down.mark_input_done()
                progress = True
        return progress

    def _harvest(self, may_block: bool) -> bool:
        owner: Dict[Any, _OpNode] = {}
        for node in self.nodes:
            for ref in node.inflight_refs():
                owner[ref] = node
        if not owner:
            return False
        refs = list(owner)
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        if not ready and may_block:
            t0 = time.perf_counter()
            ready, _ = ray_tpu.wait(
                refs, num_returns=1,
                timeout=GlobalConfig.data_straggler_wait_slice_s,
            )
            dt = time.perf_counter() - t0
            if ready:
                node = owner[ready[0]]
                if node.stats is not None:
                    node.stats.straggler_wait_s += dt
            fr.histogram(fr.DATA_STRAGGLER_WAIT_HIST, dt)
        for ref in ready:
            owner[ref].on_ready(ref)
        return bool(ready)

    # -- early exit --------------------------------------------------------
    def on_limit_satisfied(self, limit_node: _LimitNode) -> None:
        """The limit is met: every task upstream of it is moot — cancel
        in-flight refs and tear down pools instead of letting ~all of a
        large read run to completion."""
        idx = self.nodes.index(limit_node)
        self.source.clear()
        for node in self.nodes[:idx]:
            node.cancel_remaining(self)
        limit_node.mark_input_done()

    def shutdown(self) -> None:
        if self._shut:
            return
        self._shut = True
        self.source.clear()
        for node in self.nodes:
            if not node.done:
                node.cancel_remaining(self)
