"""Distributed hash joins for Datasets.

Reference: ray ``python/ray/data/_internal/execution/operators/join.py`` +
``hash_shuffle.py`` — both sides of the join are hash-partitioned on the
key into N partitions (a two-sided exchange over ``num_returns=N`` map
tasks), then one reduce task per partition builds a hash table from its
right-side rows and probes it with its left-side rows.  Inner and left
joins ship first (the reference's ``JoinType``); the reduce is
partition-local so join memory is bounded by the largest partition, not
the dataset.
"""

from __future__ import annotations

from typing import List, Optional

import ray_tpu

from .block import Block, row_key, stable_hash


@ray_tpu.remote
def _join_partition_map(item, transforms, n_out: int, key) -> List[Block]:
    """Hash-partition one block's rows by join key into n_out partitions."""
    from .execution import HashPartition, apply_chain
    from .block import ColumnarBlock

    block = apply_chain(item, transforms)
    if isinstance(block, ColumnarBlock) and isinstance(key, str):
        # Vectorized fast path (same scalar/vector hash equality contract
        # as the shuffle map): numeric key columns partition in numpy.
        pidx = HashPartition(key).vector_parts(block, n_out, 0)
        if pidx is not None:
            from .block import partition_columnar

            cparts = partition_columnar(block, pidx, n_out)
            return cparts if n_out > 1 else cparts[0]
    parts: List[Block] = [[] for _ in range(n_out)]
    for row in block:
        # stable_hash, NOT builtin hash(): str hashing is seed-randomized
        # per process, and the two sides partition in different workers.
        parts[stable_hash(row_key(row, key)) % n_out].append(row)
    # num_returns=1 returns the value VERBATIM (no list splitting), so a
    # single-partition exchange must return the bare block — the nested
    # [rows] wrapper made every 1-partition join iterate lists as "rows".
    return parts if n_out > 1 else parts[0]


@ray_tpu.remote
def _join_reduce(
    how: str, left_key, right_key, n_left: int, *parts: Block
) -> Block:
    """Join one partition: build on the right side, probe with the left."""
    left_rows = [r for p in parts[:n_left] for r in p]
    right_rows = [r for p in parts[n_left:] for r in p]
    table: dict = {}
    for row in right_rows:
        table.setdefault(row_key(row, right_key), []).append(row)
    out: Block = []
    for lrow in left_rows:
        matches = table.get(row_key(lrow, left_key))
        if matches:
            for rrow in matches:
                if isinstance(lrow, dict) and isinstance(rrow, dict):
                    out.append({**rrow, **lrow})  # left wins column clashes
                else:
                    out.append((lrow, rrow))
        elif how == "left":
            out.append(dict(lrow) if isinstance(lrow, dict) else (lrow, None))
    return out


class JoinStage:
    """Two-sided exchange stage.  Consumes the left stream; the right
    dataset executes its own plan and feeds the same partition space."""

    def __init__(self, right_ds, on, right_on=None, how: str = "inner",
                 num_partitions: Optional[int] = None):
        if how not in ("inner", "left"):
            raise ValueError(f"unsupported join type {how!r}")
        self.right_ds = right_ds
        self.on = on
        self.right_on = right_on if right_on is not None else on
        self.how = how
        self.num_partitions = num_partitions
        self.fused_transforms: List = []

    @property
    def name(self) -> str:
        return f"Join[{self.how}]"

    def with_fused(self, transforms):
        copy = JoinStage(
            self.right_ds, self.on, self.right_on, self.how,
            self.num_partitions,
        )
        copy.fused_transforms = list(transforms)
        return copy

    def run(self, upstream, stats):
        from .execution import OpStats

        st = OpStats(self.name)
        stats.append(st)
        left_items = list(upstream)  # barrier (exchange)
        right_items = list(self.right_ds._execute())
        n_out = self.num_partitions or max(1, len(left_items))

        def partition(items, transforms, key):
            out = []
            for item in items:
                st.num_tasks += 1
                refs = _join_partition_map.options(num_returns=n_out).remote(
                    item, transforms, n_out, key
                )
                out.append([refs] if n_out == 1 else refs)
            return out

        left_parts = partition(left_items, self.fused_transforms, self.on)
        right_parts = partition(right_items, [], self.right_on)
        for j in range(n_out):
            st.num_tasks += 1
            lp = [left_parts[i][j] for i in range(len(left_parts))]
            rp = [right_parts[i][j] for i in range(len(right_parts))]
            yield _join_reduce.remote(
                self.how, self.on, self.right_on, len(lp), *lp, *rp
            )
