"""Pluggable filesystems for Data IO — local, in-cluster, and remote URIs.

Reference: ray ``python/ray/data/datasource/file_based_datasource.py`` +
``path_util.py`` — every datasource/datasink resolves paths through
pyarrow filesystems (fsspec-compatible), so ``gs://bucket/...`` and
``s3://...`` ride the same read/write code as local paths.  Here the
contract is a small scheme-keyed registry:

  - plain paths / ``file://`` → ``LocalFileSystem`` (os + glob);
  - ``memory://...`` → ``MemoryFileSystem`` over the cluster control
    plane's KV (namespace ``datafs``) — the in-cluster remote used by
    tests AND a real cross-node store: any worker can read blocks any
    other worker wrote, like an object-store bucket (the same backing
    the Train checkpoint layer's ``memory://`` storage uses);
  - other schemes (``gs://``, ``s3://``) → whatever the deployment
    registers via ``register_filesystem`` (zero-egress boxes can't
    reach real buckets; the seam is the point).

Readers that need a real OS path (tarfile, wave, cv2, pyarrow dataset
scans) call ``ensure_local`` — remote files materialize in a temp file,
local paths pass through untouched (the fsspec local-cache pattern).
Writers produce a local file then ``publish`` it to the destination URI.
"""

from __future__ import annotations

import fnmatch
import glob as _glob
import os
import shutil
import tempfile
from typing import Dict, List, Tuple

_SCHEME_SEP = "://"


def _scheme_of(path: str) -> str:
    i = path.find(_SCHEME_SEP)
    # Windows-style drive letters don't appear here; any single-token
    # prefix before :// is a scheme.
    return path[:i] if i > 0 else ""


class DataFileSystem:
    """Contract for a URI scheme.  All methods take FULL URIs."""

    def glob(self, pattern: str) -> List[str]:
        raise NotImplementedError

    def isdir(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        """Atomic whole-file write (the manifest-commit primitive)."""
        raise NotImplementedError

    def ensure_local(self, path: str) -> str:
        """A real OS path with this file's contents (identity for local)."""
        raise NotImplementedError

    def publish(self, local_file: str, dest: str) -> None:
        """Upload a finished local file to ``dest`` (no-op for local)."""
        raise NotImplementedError

    def join(self, base: str, *parts: str) -> str:
        return "/".join([base.rstrip("/")] + [p.strip("/") for p in parts])


class LocalFileSystem(DataFileSystem):
    @staticmethod
    def _strip(path: str) -> str:
        return path[len("file://"):] if path.startswith("file://") else path

    def glob(self, pattern: str) -> List[str]:
        return sorted(_glob.glob(self._strip(pattern)))

    def isdir(self, path: str) -> bool:
        return os.path.isdir(self._strip(path))

    def makedirs(self, path: str) -> None:
        os.makedirs(self._strip(path), exist_ok=True)

    def read_bytes(self, path: str) -> bytes:
        with open(self._strip(path), "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        p = self._strip(path)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def ensure_local(self, path: str) -> str:
        return self._strip(path)

    def publish(self, local_file: str, dest: str) -> None:
        d = self._strip(dest)
        if os.path.abspath(local_file) != os.path.abspath(d):
            shutil.copyfile(local_file, d)

    def join(self, base: str, *parts: str) -> str:
        if base.startswith("file://"):
            return super().join(base, *parts)
        return os.path.join(base, *parts)


class MemoryFileSystem(DataFileSystem):
    """Cluster-KV-backed files (namespace ``datafs``), one key per file.

    Works from any driver or worker in the session — reads and writes go
    through the control plane, so a block written by one node is readable
    by every other (the test-and-CI stand-in for a bucket)."""

    _NS = "datafs"

    @staticmethod
    def _worker():
        from ray_tpu.api import global_worker

        return global_worker()

    def _keys(self, prefix: str) -> List[str]:
        return self._worker().kv_keys(self._NS, prefix=prefix)

    def glob(self, pattern: str) -> List[str]:
        # Prefix scan up to the first wildcard, then match.
        cut = len(pattern)
        for ch in "*?[":
            i = pattern.find(ch)
            if i != -1:
                cut = min(cut, i)
        keys = self._keys(pattern[:cut])
        if cut == len(pattern):  # no wildcard: exact file or directory
            return sorted(
                k for k in keys
                if k == pattern or k.startswith(pattern.rstrip("/") + "/")
            )
        # Segment-wise fnmatch: raw fnmatch lets '*' cross '/', so
        # 'memory://dir/*' would also match files in nested
        # subdirectories — diverging from LocalFileSystem/glob semantics
        # and double-reading partitioned layouts (dir/part=0/f.parquet
        # matched by both the dir scan and the partition scan).
        parts = pattern.split("/")
        return sorted(
            k for k in keys
            if len(k.split("/")) == len(parts)
            and all(
                fnmatch.fnmatch(seg, pat)
                for seg, pat in zip(k.split("/"), parts)
            )
        )

    def isdir(self, path: str) -> bool:
        prefix = path.rstrip("/") + "/"
        return any(k.startswith(prefix) for k in self._keys(prefix))

    def makedirs(self, path: str) -> None:
        pass  # directories are implicit in key names

    def read_bytes(self, path: str) -> bytes:
        data = self._worker().kv_get(self._NS, path)
        if data is None:
            raise FileNotFoundError(path)
        return data

    def write_bytes(self, path: str, data: bytes) -> None:
        self._worker().kv_put(self._NS, path, bytes(data))

    # Per-process materialization cache: datafs blocks are write-once
    # (sinks never rewrite a part file), so one temp copy per path serves
    # every read task in this worker — without it, N row-group tasks over
    # one file would download N full copies, and pooled workers are
    # long-lived.  Entries unlink at interpreter exit.
    _local_cache: Dict[str, str] = {}

    def ensure_local(self, path: str) -> str:
        cached = self._local_cache.get(path)
        if cached is not None and os.path.exists(cached):
            return cached
        data = self.read_bytes(path)
        suffix = os.path.splitext(path)[1]
        fd, tmp = tempfile.mkstemp(prefix="rtpu_datafs_", suffix=suffix)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        if not self._local_cache:
            import atexit

            atexit.register(MemoryFileSystem._purge_local_cache)
        self._local_cache[path] = tmp
        return tmp

    @staticmethod
    def _purge_local_cache():
        for tmp in MemoryFileSystem._local_cache.values():
            try:
                os.unlink(tmp)
            except OSError:
                pass
        MemoryFileSystem._local_cache.clear()

    def publish(self, local_file: str, dest: str) -> None:
        with open(local_file, "rb") as f:
            self.write_bytes(dest, f.read())


_REGISTRY: Dict[str, DataFileSystem] = {
    "": LocalFileSystem(),
    "file": LocalFileSystem(),
    "memory": MemoryFileSystem(),
}


def register_filesystem(scheme: str, fs: DataFileSystem) -> None:
    """Mount a filesystem for a URI scheme (``gs``, ``s3``, ...) —
    deployment hook, mirroring pyarrow's fsspec handler registration."""
    _REGISTRY[scheme] = fs


def resolve(path: str) -> Tuple[DataFileSystem, str]:
    scheme = _scheme_of(path)
    fs = _REGISTRY.get(scheme)
    if fs is None:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(path {path!r}); call "
            "ray_tpu.data.filesystem.register_filesystem("
            f"{scheme!r}, fs) to mount one"
        )
    return fs, path


def is_uri(path: str) -> bool:
    return bool(_scheme_of(path))


def ensure_local(path: str) -> str:
    fs, p = resolve(path)
    return fs.ensure_local(p)


def fs_join(base: str, *parts: str) -> str:
    fs, b = resolve(base)
    return fs.join(b, *parts)
