"""Avro Object Container File codec — dependency-free.

Reference: ray ``python/ray/data/_internal/datasource/avro_datasource.py``
reads Avro through the ``fastavro`` package.  Neither ``avro`` nor
``fastavro`` is available here, so — like ``data/tfrecord.py`` for
TFRecord — this module implements the container-file framing and the
schema-driven binary encoding directly from the Avro 1.11 spec:

* OCF layout: ``Obj\\x01`` magic, metadata map (``avro.schema`` JSON +
  ``avro.codec``), 16-byte sync marker, then data blocks of
  ``(row_count, byte_size, payload, sync)``.
* Codecs: ``null`` and ``deflate`` (raw DEFLATE via zlib, wbits=-15).
* Types: null/boolean/int/long/float/double/bytes/string/record/enum/
  array/map/union/fixed; logical types decode as their underlying type.

Longs are zigzag varints; arrays/maps are block-encoded (a negative
count is followed by a byte size and means ``abs(count)`` items).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional

MAGIC = b"Obj\x01"


# ------------------------------------------------------------- primitives
def _read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # zigzag


def _write_long(value: int) -> bytes:
    acc = (value << 1) ^ (value >> 63)  # zigzag (Python ints: arithmetic shift)
    out = bytearray()
    while True:
        bits = acc & 0x7F
        acc >>= 7
        if acc:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) != n:
        raise EOFError("truncated bytes")
    return data


# ------------------------------------------------------------ schema codec
def _decode(schema, buf: io.BytesIO):
    if isinstance(schema, list):  # union
        return _decode(schema[_read_long(buf)], buf)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {
                f["name"]: _decode(f["type"], buf) for f in schema["fields"]
            }
        if t == "enum":
            return schema["symbols"][_read_long(buf)]
        if t == "array":
            out = []
            while True:
                count = _read_long(buf)
                if count == 0:
                    return out
                if count < 0:
                    _read_long(buf)  # block byte size: skippable, unused
                    count = -count
                for _ in range(count):
                    out.append(_decode(schema["items"], buf))
        if t == "map":
            out = {}
            while True:
                count = _read_long(buf)
                if count == 0:
                    return out
                if count < 0:
                    _read_long(buf)
                    count = -count
                for _ in range(count):
                    key = _read_bytes(buf).decode()
                    out[key] = _decode(schema["values"], buf)
        if t == "fixed":
            return buf.read(schema["size"])
        return _decode(t, buf)  # named/logical wrapper: unwrap
    # primitive (schema is a string)
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode()
    raise ValueError(f"unsupported avro type: {schema!r}")


def _encode(schema, value, out: bytearray) -> None:
    if isinstance(schema, list):  # union: pick the first matching branch
        for i, branch in enumerate(schema):
            if _matches(branch, value):
                out += _write_long(i)
                _encode(branch, value, out)
                return
        raise ValueError(f"value {value!r} matches no union branch {schema}")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            # .get, not []: infer_schema null-unions fields absent from
            # some rows, so encoding must tolerate the absence too.
            for f in schema["fields"]:
                _encode(f["type"], value.get(f["name"]), out)
            return
        if t == "enum":
            out += _write_long(schema["symbols"].index(value))
            return
        if t == "array":
            # len() instead of truthiness: numpy arrays are valid array
            # values and raise on bool().
            if len(value):
                out += _write_long(len(value))
                for item in value:
                    _encode(schema["items"], item, out)
            out += _write_long(0)
            return
        if t == "map":
            if len(value):
                out += _write_long(len(value))
                for k, v in value.items():
                    kb = k.encode()
                    out += _write_long(len(kb))
                    out += kb
                    _encode(schema["values"], v, out)
            out += _write_long(0)
            return
        if t == "fixed":
            out += bytes(value)
            return
        _encode(t, value, out)
        return
    if schema == "null":
        return
    if schema == "boolean":
        out.append(1 if value else 0)
        return
    if schema in ("int", "long"):
        out += _write_long(int(value))
        return
    if schema == "float":
        out += struct.pack("<f", float(value))
        return
    if schema == "double":
        out += struct.pack("<d", float(value))
        return
    if schema == "bytes":
        out += _write_long(len(value))
        out += bytes(value)
        return
    if schema == "string":
        data = str(value).encode()
        out += _write_long(len(data))
        out += data
        return
    raise ValueError(f"unsupported avro type: {schema!r}")


def _matches(schema, value) -> bool:
    # numpy scalar types count as their python analogs — ColumnarBlock
    # iteration yields np.int64/np.float32/np.bool_ values and
    # infer_schema/_type_name already accept them.
    import numpy as np

    t = schema["type"] if isinstance(schema, dict) else schema
    if t == "null":
        return value is None
    if t == "boolean":
        return isinstance(value, (bool, np.bool_))
    if t in ("int", "long"):
        return isinstance(value, (int, np.integer)) and not isinstance(
            value, (bool, np.bool_)
        )
    if t in ("float", "double"):
        return isinstance(value, (float, np.floating))
    if t == "string":
        return isinstance(value, str)
    if t in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if t == "array":
        return isinstance(value, (list, np.ndarray))
    if t in ("map", "record"):
        return isinstance(value, dict)
    if t == "enum":
        return isinstance(value, str)
    return value is not None


def infer_schema(rows: List[Dict[str, Any]], name: str = "Row") -> dict:
    """Record schema from sample rows; a column whose values include None
    becomes a ``["null", T]`` union."""

    def of(values, field):
        types = set()
        for v in values:
            if v is None:
                types.add("null")
            else:
                types.add(_type_name(v))
        types.discard("null")
        if len(types) > 1:
            raise ValueError(f"mixed types for field {field!r}: {types}")
        base: Any = next(iter(types)) if types else "null"
        if base == "array":
            # len() guards, not truthiness — ndarray columns raise on bool()
            items = [x for v in values
                     if v is not None and len(v) for x in v]
            # Recurse: an array of maps/arrays needs the FULL nested
            # schema ({"type": "map", "values": ...}), not the bare type
            # name — _encode rejects bare "map"/"array".
            base = {"type": "array",
                    "items": of(items, f"{field}[]") if items else "string"}
        elif base == "map":
            vals = [x for v in values
                    if v is not None and len(v) for x in v.values()]
            base = {"type": "map",
                    "values": of(vals, f"{field}{{}}") if vals else "string"}
        if any(v is None for v in values) and base != "null":
            return ["null", base]
        return base

    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    return {
        "type": "record",
        "name": name,
        "fields": [
            {"name": k, "type": of([r.get(k) for r in rows], k)} for k in keys
        ],
    }


def _type_name(v) -> str:
    import numpy as np

    if isinstance(v, bool) or isinstance(v, np.bool_):
        return "boolean"
    if isinstance(v, (int, np.integer)):
        return "long"
    if isinstance(v, (float, np.floating)):
        return "double"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (bytes, bytearray)):
        return "bytes"
    if isinstance(v, (list, np.ndarray)):
        return "array"
    if isinstance(v, dict):
        return "map"
    raise ValueError(f"cannot infer avro type of {type(v)}")


# ------------------------------------------------------------------- files
def read_avro_file(path: str) -> List[Dict[str, Any]]:
    """All rows of one OCF file.  Top-level record schemas yield dict rows;
    any other top-level type yields ``{"value": v}`` rows."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        count = _read_long(buf)
        if count == 0:
            break
        if count < 0:
            _read_long(buf)
            count = -count
        for _ in range(count):
            key = _read_bytes(buf).decode()
            meta[key] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = buf.read(16)
    is_record = isinstance(schema, dict) and schema.get("type") == "record"
    rows: List[Dict[str, Any]] = []
    while buf.tell() < len(data):
        n_rows = _read_long(buf)
        payload = _read_bytes(buf)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec: {codec}")
        block = io.BytesIO(payload)
        for _ in range(n_rows):
            v = _decode(schema, block)
            rows.append(v if is_record else {"value": v})
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt file)")
    return rows


def write_avro_file(rows: List[Dict[str, Any]], path: str,
                    schema: Optional[dict] = None,
                    codec: str = "null") -> str:
    schema = schema or infer_schema(rows or [{}])
    body = bytearray()
    for r in rows:
        _encode(schema, r, body)
    payload = bytes(body)
    if codec == "deflate":
        payload = zlib.compress(payload, 9)[2:-4]  # strip zlib header+adler
    elif codec != "null":
        raise ValueError(f"unsupported avro codec: {codec}")
    sync = os.urandom(16)
    out = bytearray(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out += _write_long(len(meta))
    for k, v in meta.items():
        kb = k.encode()
        out += _write_long(len(kb))
        out += kb
        out += _write_long(len(v))
        out += v
    out += _write_long(0)
    out += sync
    if rows:
        out += _write_long(len(rows))
        out += _write_long(len(payload))
        out += payload
        out += sync
    with open(path, "wb") as f:
        f.write(out)
    return path
