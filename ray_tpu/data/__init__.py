from .dataset import (  # noqa: F401
    DataIterator,
    Dataset,
    from_items,
    range_dataset,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
)
