from .aggregate import (  # noqa: F401
    AggregateFn,
    Count,
    GroupedData,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from .datasource import Datasource, ReadTask  # noqa: F401
from .execution import ActorPoolStrategy  # noqa: F401
from .streaming import ExecutionOptions  # noqa: F401
from .arrow import from_arrow  # noqa: F401
from .interop import from_huggingface, from_pandas, from_torch  # noqa: F401
from .datasink import (  # noqa: F401
    AvroDatasink,
    CSVDatasink,
    Datasink,
    ImageDatasink,
    JSONDatasink,
    ManifestedDatasink,
    NumpyDatasink,
    ParquetDatasink,
    SQLDatasink,
    TFRecordsDatasink,
    WebDatasetDatasink,
)
from .warehouse import (  # noqa: F401
    BigQueryDatasource,
    ClickHouseDatasource,
    IcebergDatasource,
    KafkaDatasink,
    KafkaDatasource,
    MongoDatasink,
    MongoDatasource,
)
from .dataset import (  # noqa: F401
    DataIterator,
    Dataset,
    from_blocks,
    from_items,
    range_dataset,
    read_audio,
    read_avro,
    read_binary_files,
    read_images,
    read_bigquery,
    read_clickhouse,
    read_iceberg,
    read_kafka,
    read_mongo,
    read_sql,
    read_tfrecords,
    read_videos,
    read_webdataset,
    read_csv,
    read_datasource,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)
