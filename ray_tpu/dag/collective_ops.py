"""In-graph collectives: allreduce across a set of actors' DAG nodes.

Reference: ray ``python/ray/dag/collective_node.py:23,252`` — binding an
allreduce over per-actor computation nodes so the exchange happens inside
the compiled graph, overlapping with the pipeline.  Here the exchange rides
the same shm channels as every other compiled edge: each participant reads
the other participants' values and reduces locally (host tensors; on-chip
tensors inside one jitted step should use ``jax.lax.psum`` instead — the
channel path is for cross-actor orchestration).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .nodes import ClassMethodNode, DAGNode

RESERVED_COLLECTIVE_METHOD = "__rtpu_dag_collective__"

_OPS = ("sum", "mean", "max", "min", "product")


def apply_collective(op: str, tensors: Sequence) -> np.ndarray:
    arrays = [np.asarray(t) for t in tensors]
    stacked = np.stack(arrays)
    if op == "sum":
        return stacked.sum(axis=0)
    if op == "mean":
        return stacked.mean(axis=0)
    if op == "max":
        return stacked.max(axis=0)
    if op == "min":
        return stacked.min(axis=0)
    if op == "product":
        return stacked.prod(axis=0)
    raise ValueError(f"unknown collective op {op!r} (one of {_OPS})")


class CollectiveOpNode(ClassMethodNode):
    """One participant's view of an in-graph allreduce: consumes every
    participant's upstream value, emits the reduced tensor on this
    participant's actor."""

    def __init__(self, actor_handle, participants: Sequence[DAGNode],
                 op: str, group_name: str = None):
        kwargs = {"_op": op}
        if group_name is not None:
            kwargs["_group"] = group_name
        super().__init__(
            actor_handle,
            RESERVED_COLLECTIVE_METHOD,
            tuple(participants),
            kwargs,
        )

    def _execute_impl(self, cache, input_args, input_kwargs):
        # Classic (uncompiled) path: gather the participant refs and reduce
        # driver-side (the compiled path reduces inside each actor's loop).
        # Sibling outputs of the same allreduce share ONE reduction via the
        # per-execute cache — N outputs must not mean N gathers.
        import ray_tpu

        group_key = (
            "__rtpu_allreduce__",
            tuple(id(a) for a in self._bound_args),
            self._bound_kwargs["_op"],
        )
        if group_key in cache:
            return cache[group_key]
        refs = [
            self._resolve_arg(a, cache, input_args, input_kwargs)
            for a in self._bound_args
        ]
        values = [
            ray_tpu.get(r, timeout=300)
            if isinstance(r, ray_tpu.ObjectRef)
            else r
            for r in refs
        ]
        result = ray_tpu.put(
            apply_collective(self._bound_kwargs["_op"], values)
        )
        cache[group_key] = result
        return result


def allreduce_bind(
    nodes: Sequence[ClassMethodNode], op: str = "sum",
    group_name: str = None,
) -> List[CollectiveOpNode]:
    """Bind an allreduce across per-actor nodes; returns one output node per
    participant (reference: ``ray.experimental.collective.allreduce.bind``).

        with InputNode() as inp:
            partials = [w.compute.bind(inp) for w in workers]
            reduced = allreduce_bind(partials)
            dag = MultiOutputNode(reduced)
    """
    if op not in _OPS:
        raise ValueError(f"unknown collective op {op!r} (one of {_OPS})")
    if len(nodes) < 2:
        raise ValueError("allreduce requires at least 2 participants")
    actor_ids = {n._actor._actor_id for n in nodes}
    if len(actor_ids) != len(nodes):
        raise ValueError("each participant must live on a distinct actor")
    # group_name: participants reduce through the named collective group's
    # device op (psum over the group mesh — ICI on a TPU slice with the
    # xla backend) instead of the host numpy reduction.
    return [CollectiveOpNode(n._actor, nodes, op, group_name) for n in nodes]
