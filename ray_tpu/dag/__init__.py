"""Lazy DAGs over tasks/actors + ahead-of-time compiled execution.

Equivalent of the reference's ``python/ray/dag/`` (classic DAG API +
Compiled Graphs/aDAG).  Build graphs with ``.bind()``, run them either as
ordinary task/actor submissions (``dag.execute()``) or compiled into
channel-driven per-actor loops (``dag.experimental_compile()``).
"""

from .collective_ops import CollectiveOpNode, allreduce_bind
from .compiled import CompiledDAG, CompiledDAGRef, DAGError
from .nodes import (
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "CollectiveOpNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGError",
    "ClassMethodNode",
    "DAGNode",
    "FunctionNode",
    "InputAttributeNode",
    "InputNode",
    "MultiOutputNode",
    "allreduce_bind",
]
