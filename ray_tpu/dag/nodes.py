"""DAG node types for lazy task/actor graphs.

Equivalent of the reference's ``python/ray/dag/dag_node.py:32`` (DAGNode),
``input_node.py`` (InputNode/InputAttributeNode), ``class_node.py``
(ClassMethodNode), and ``output_node.py`` (MultiOutputNode).  Nodes are
built with ``.bind()`` and either executed lazily as ordinary tasks/actor
calls (``execute()``) or compiled into a static channel-driven pipeline
(``experimental_compile()`` → ``ray_tpu.dag.compiled``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

_input_ctx = threading.local()


class DAGNode:
    """Base class: a lazily-bound computation with upstream dependencies."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs)

    def upstream(self) -> List["DAGNode"]:
        ups = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                ups.append(a)
        return ups

    # -- classic (uncompiled) execution ------------------------------------
    def execute(self, *input_args, **input_kwargs):
        """Recursively submit the graph as ordinary tasks/actor calls and
        return the resulting ObjectRef(s) (reference: DAGNode.execute)."""
        cache: Dict[int, Any] = {}
        return self._execute_node(cache, input_args, input_kwargs)

    def _resolve_arg(self, a, cache, input_args, input_kwargs):
        if isinstance(a, DAGNode):
            return a._execute_node(cache, input_args, input_kwargs)
        return a

    def _execute_node(self, cache, input_args, input_kwargs):
        key = id(self)
        if key not in cache:
            cache[key] = self._execute_impl(cache, input_args, input_kwargs)
        return cache[key]

    def _execute_impl(self, cache, input_args, input_kwargs):
        raise NotImplementedError

    # -- compiled execution -------------------------------------------------
    def experimental_compile(self, buffer_size_bytes: int = 8 * 1024 * 1024):
        from .compiled import CompiledDAG

        return CompiledDAG(self, buffer_size_bytes=buffer_size_bytes)


class InputNode(DAGNode):
    """The DAG's formal parameter.  Use as a context manager:

        with InputNode() as inp:
            out = actor.fwd.bind(inp)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        _input_ctx.node = self
        return self

    def __exit__(self, *exc):
        _input_ctx.node = None

    def __getitem__(self, key):
        return InputAttributeNode(self, key)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name)

    def _execute_impl(self, cache, input_args, input_kwargs):
        if input_kwargs:
            raise ValueError(
                "kwargs passed to execute() require InputAttributeNode access"
            )
        if len(input_args) == 1:
            return input_args[0]
        return tuple(input_args)


class InputAttributeNode(DAGNode):
    """``inp[i]`` / ``inp.key`` — selects one piece of the DAG input."""

    def __init__(self, parent: InputNode, key):
        super().__init__((parent,), {})
        self._key = key

    def _execute_impl(self, cache, input_args, input_kwargs):
        if isinstance(self._key, int):
            return input_args[self._key]
        return input_kwargs[self._key]


class ClassMethodNode(DAGNode):
    """A bound actor-method call (reference: ClassMethodNode)."""

    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._actor = actor_handle
        self._method_name = method_name

    def _execute_impl(self, cache, input_args, input_kwargs):
        args = [
            self._resolve_arg(a, cache, input_args, input_kwargs)
            for a in self._bound_args
        ]
        kwargs = {
            k: self._resolve_arg(v, cache, input_args, input_kwargs)
            for k, v in self._bound_kwargs.items()
        }
        import ray_tpu

        # Upstream results here are ObjectRefs (from .remote); pass them
        # through so the runtime resolves them (zero extra copies), except
        # plain input values which are passed as-is.
        method = getattr(self._actor, self._method_name)
        return method.remote(*args, **kwargs)


class FunctionNode(DAGNode):
    """A bound remote-function call (reference: FunctionNode)."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, cache, input_args, input_kwargs):
        args = [
            self._resolve_arg(a, cache, input_args, input_kwargs)
            for a in self._bound_args
        ]
        kwargs = {
            k: self._resolve_arg(v, cache, input_args, input_kwargs)
            for k, v in self._bound_kwargs.items()
        }
        return self._remote_fn.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Marks multiple leaves as the DAG output (reference: MultiOutputNode)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _execute_impl(self, cache, input_args, input_kwargs):
        return [
            self._resolve_arg(a, cache, input_args, input_kwargs)
            for a in self._bound_args
        ]


def topological_order(root: DAGNode) -> List[DAGNode]:
    """Deterministic post-order (upstream before downstream)."""
    seen: Dict[int, DAGNode] = {}
    order: List[DAGNode] = []

    def visit(n: DAGNode):
        if id(n) in seen:
            return
        seen[id(n)] = n
        for u in n.upstream():
            visit(u)
        order.append(n)

    visit(root)
    return order
