"""Actor-side execution loop for compiled DAGs.

Runs inside the actor's worker process as one long-lived task (dispatched by
the core worker under the reserved method name ``__rtpu_dag_exec_loop__``).
Per tick it reads its input channels, executes the actor's bound methods in
topological order, and writes output channels — the analog of the
reference's per-actor ``do_exec_tasks`` loop (ray
``python/ray/dag/compiled_dag_node.py:125``).
"""

from __future__ import annotations

from typing import Any, Dict

from ..core.native import ChannelClosedError, NativeChannel
from ..core.serialization import deserialize_from_bytes, serialize_to_bytes


class _Err:
    """An upstream error flowing through the pipeline: ops forward it to
    their outputs without executing."""

    __slots__ = ("payload",)

    def __init__(self, payload: bytes):
        self.payload = payload


def dag_exec_loop(instance, plan: Dict[str, Any]) -> str:
    """Execute the per-actor plan until any channel closes.

    plan = {"input_path": str|None,
            "ops": [{"idx", "method", "args", "kwargs", "out_path"}, ...]}
    arg spec: ("const", v) | ("chan", path) | ("local", node_idx)
            | ("input", None|int|str)
    """
    chans: Dict[str, NativeChannel] = {}

    def chan(path: str) -> NativeChannel:
        ch = chans.get(path)
        if ch is None:
            ch = NativeChannel.attach(path)
            chans[path] = ch
        return ch

    needs_input = any(
        spec[0] == "input"
        for op in plan["ops"]
        for spec in list(op["args"]) + list(op["kwargs"].values())
    )

    try:
        while True:
            tick_chan_vals: Dict[str, Any] = {}
            input_val: Any = None
            if needs_input:
                data, err = chan(plan["input_path"]).read()
                input_val = _Err(data) if err else deserialize_from_bytes(data)
            local_vals: Dict[int, Any] = {}

            def resolve(spec):
                kind, ref = spec
                if kind == "const":
                    return ref
                if kind == "local":
                    return local_vals[ref]
                if kind == "chan":
                    if ref not in tick_chan_vals:
                        data, err = chan(ref).read()
                        tick_chan_vals[ref] = (
                            _Err(data) if err else deserialize_from_bytes(data)
                        )
                    return tick_chan_vals[ref]
                if kind == "input":
                    if isinstance(input_val, _Err):
                        return input_val
                    in_args, in_kwargs = input_val
                    if ref is None:
                        if in_kwargs:
                            raise ValueError("kwargs require input attribute access")
                        return in_args[0] if len(in_args) == 1 else tuple(in_args)
                    if isinstance(ref, int):
                        return in_args[ref]
                    return in_kwargs[ref]
                raise ValueError(f"bad arg spec {spec!r}")

            for op in plan["ops"]:
                # Any per-op failure — bad input selection, method raise, or
                # unserializable/oversized result — becomes a pipeline error
                # delivered to the driver; only channel closure (teardown)
                # may end the loop.
                try:
                    args = [resolve(s) for s in op["args"]]
                    kwargs = {k: resolve(s) for k, s in op["kwargs"].items()}
                except ChannelClosedError:
                    raise
                except BaseException as e:  # noqa: BLE001
                    args, kwargs = [_Err(serialize_to_bytes(e))], {}
                upstream_err = next(
                    (a for a in list(args) + list(kwargs.values()) if isinstance(a, _Err)),
                    None,
                )
                if upstream_err is not None:
                    result: Any = upstream_err
                else:
                    try:
                        from .collective_ops import (
                            RESERVED_COLLECTIVE_METHOD,
                            apply_collective,
                        )

                        if op["method"] == RESERVED_COLLECTIVE_METHOD:
                            group_name = kwargs.get("_group")
                            if group_name is not None:
                                # Device-path reduction: psum over the
                                # bound collective group's mesh (the same
                                # path DeviceRef transfers ride; ICI with
                                # the xla backend on a real slice).
                                from ray_tpu.collective import (
                                    ReduceOp, allreduce,
                                )

                                _rop = {
                                    "sum": ReduceOp.SUM,
                                    "mean": ReduceOp.MEAN,
                                    "max": ReduceOp.MAX,
                                    "min": ReduceOp.MIN,
                                    "product": ReduceOp.PRODUCT,
                                }[kwargs["_op"]]
                                import numpy as _np

                                from ray_tpu.collective import get_group
                                from ray_tpu.collective.local_group import (
                                    LocalXlaGroup,
                                )

                                group = get_group(group_name)
                                if isinstance(group, LocalXlaGroup):
                                    # Single-process simulator: its API
                                    # takes the full per-rank tensor list.
                                    outs = group.allreduce(list(args), _rop)
                                    result = _np.asarray(outs[0])
                                else:
                                    # Multi-process backend (xla): each
                                    # rank contributes ONLY its own shard —
                                    # participants are bound in rank order,
                                    # so this actor's value is args[rank].
                                    own = (
                                        args[group.rank]
                                        if len(args) > 1
                                        else args[0]
                                    )
                                    result = _np.asarray(
                                        group.allreduce(own, _rop)
                                    )
                            else:
                                # Host fallback: numpy reduction over the
                                # channel-delivered values.
                                result = apply_collective(
                                    kwargs["_op"], args
                                )
                        else:
                            result = getattr(instance, op["method"])(
                                *args, **kwargs
                            )
                    except BaseException as e:  # noqa: BLE001 — becomes a pipeline error
                        result = _Err(serialize_to_bytes(e))
                local_vals[op["idx"]] = result
                if op["out_path"] is not None:
                    out = chan(op["out_path"])
                    if isinstance(result, _Err):
                        out.write(result.payload, error=1)
                    else:
                        try:
                            payload = serialize_to_bytes(result)
                            if len(payload) > out.capacity:
                                raise ValueError(
                                    f"DAG op {op['method']!r} result of "
                                    f"{len(payload)} bytes exceeds the channel "
                                    f"buffer ({out.capacity}); recompile with a "
                                    f"larger buffer_size_bytes"
                                )
                        except BaseException as e:  # noqa: BLE001
                            local_vals[op["idx"]] = _Err(serialize_to_bytes(e))
                            out.write(local_vals[op["idx"]].payload, error=1)
                        else:
                            out.write(payload)
    except ChannelClosedError:
        return "closed"
    finally:
        for ch in chans.values():
            ch.detach()
