"""Compiled static DAGs over actors — channel-driven execution.

Equivalent of the reference's ``python/ray/dag/compiled_dag_node.py:805``
(CompiledDAG): at compile time every edge of the graph becomes a
shared-memory mutable-object channel (ray
``experimental/channel/shared_memory_channel.py``), and every participating
actor starts a long-lived execution loop that reads its input channels,
runs the bound method, and writes its output channel — no per-call RPC,
scheduling, or serialization of the graph structure on the hot path.

TPU note: channel payloads are host bytes.  Device-resident jax.Arrays
handed between actors on the same host transfer via shm once (device→host
→device); cross-slice tensor movement belongs to the collective layer
(ray_tpu.collective), exactly as NCCL channels do in the reference.
"""

from __future__ import annotations

import logging
import os
import secrets
from typing import Any, Dict, List, Optional, Tuple

from ..core import shm
from ..core.native import NativeChannel, ChannelClosedError, available as native_available
from ..core.serialization import deserialize_from_bytes, serialize_to_bytes
from .nodes import (
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
    topological_order,
)


logger = logging.getLogger(__name__)


class DAGError(RuntimeError):
    pass


class CompiledDAG:
    """A compiled static graph.  ``execute()`` pushes one input through the
    pipeline; results are read in submission order via the returned ref's
    ``get()``."""

    def __init__(self, root: DAGNode, buffer_size_bytes: int = 8 * 1024 * 1024):
        if not native_available():
            raise RuntimeError(
                "compiled DAGs require the native channel library "
                "(build/librtpu_native.so)"
            )
        from ..core.core_worker import global_worker

        self._worker = global_worker()
        self._session = self._worker.session_id
        self._dag_id = secrets.token_hex(4)
        self._buffer = buffer_size_bytes
        self._channels: List[NativeChannel] = []
        self._loop_refs = []
        self._pending = 0
        self._torn_down = False

        self._build(root)

    # ------------------------------------------------------------- building
    def _chan_path(self, idx: str) -> str:
        return os.path.join(
            shm.SHM_DIR, f"{shm._PREFIX}_{self._session}_dag{self._dag_id}_{idx}"
        )

    def _build(self, root: DAGNode):
        if isinstance(root, MultiOutputNode):
            output_nodes = list(root._bound_args)
        else:
            output_nodes = [root]
        self._n_outputs = len(output_nodes)
        self._multi = isinstance(root, MultiOutputNode)

        order = [
            n
            for n in topological_order(root)
            if isinstance(n, ClassMethodNode)
        ]
        if not order:
            raise DAGError("compiled DAG must contain at least one actor method")
        for n in topological_order(root):
            if isinstance(n, FunctionNode):
                raise DAGError(
                    "compiled DAGs support actor methods only (bind methods on "
                    "actors; plain task nodes run via .execute())"
                )

        node_idx = {id(n): i for i, n in enumerate(order)}

        # Decide, per compute node, who consumes its value.
        consumer_actors: Dict[int, set] = {i: set() for i in range(len(order))}
        input_consumers: set = set()
        for n in order:
            actor_key = n._actor._actor_id
            for a in list(n._bound_args) + list(n._bound_kwargs.values()):
                if isinstance(a, ClassMethodNode):
                    j = node_idx[id(a)]
                    if a._actor._actor_id != actor_key:
                        consumer_actors[j].add(actor_key)
                elif isinstance(a, (InputNode, InputAttributeNode)):
                    input_consumers.add(actor_key)

        for n in output_nodes:
            if not isinstance(n, ClassMethodNode):
                raise DAGError("DAG outputs must be actor method nodes")
        is_output = {node_idx[id(n)] for n in output_nodes}

        # Create channels: input channel + one per node that needs one.  A
        # DAG whose ops never read the input gets no input channel at all
        # (writing to a reader-less channel would wedge the second execute).
        self._input_chan = None
        if input_consumers:
            self._input_chan = NativeChannel.create(
                self._chan_path("in"), self._buffer, n_readers=len(input_consumers)
            )
            self._channels.append(self._input_chan)

        node_chan_path: Dict[int, Optional[str]] = {}
        self._output_chans: Dict[int, NativeChannel] = {}
        for i, n in enumerate(order):
            n_readers = len(consumer_actors[i]) + (1 if i in is_output else 0)
            if n_readers == 0:
                node_chan_path[i] = None
                continue
            path = self._chan_path(f"n{i}")
            ch = NativeChannel.create(path, self._buffer, n_readers=n_readers)
            self._channels.append(ch)
            node_chan_path[i] = path
            if i in is_output:
                self._output_chans[i] = ch

        # Per-actor plans.
        plans: Dict[Any, dict] = {}
        handles: Dict[Any, Any] = {}
        for i, n in enumerate(order):
            key = n._actor._actor_id
            handles[key] = n._actor
            plan = plans.setdefault(
                key,
                {
                    "ops": [],
                    "input_path": self._input_chan.path if self._input_chan else None,
                },
            )

            def argspec(a):
                if isinstance(a, ClassMethodNode):
                    j = node_idx[id(a)]
                    if a._actor._actor_id == key:
                        return ("local", j)
                    return ("chan", node_chan_path[j])
                if isinstance(a, InputNode):
                    return ("input", None)
                if isinstance(a, InputAttributeNode):
                    return ("input", a._key)
                return ("const", a)

            plan["ops"].append(
                {
                    "idx": i,
                    "method": n._method_name,
                    "args": [argspec(a) for a in n._bound_args],
                    "kwargs": {k: argspec(v) for k, v in n._bound_kwargs.items()},
                    "out_path": node_chan_path[i],
                }
            )

        # Start the per-actor execution loops.
        from ..core.api_frontend import ActorMethod

        for key, plan in plans.items():
            handle = handles[key]
            ref = ActorMethod(handle, "__rtpu_dag_exec_loop__").remote(plan)
            self._loop_refs.append(ref)

        # Output read order: submission order of output_nodes.
        self._output_idxs = [node_idx[id(n)] for n in output_nodes]

    # ------------------------------------------------------------ execution
    def execute(self, *args, **kwargs) -> "CompiledDAGRef":
        if self._torn_down:
            raise DAGError("DAG has been torn down")
        if self._input_chan is not None:
            payload = serialize_to_bytes((args, kwargs))
            self._input_chan.write(payload, timeout=60.0)
        elif args or kwargs:
            raise DAGError("this DAG does not consume any input")
        self._pending += 1
        return CompiledDAGRef(self)

    def _read_result(self, timeout: Optional[float]):
        """Read one execution's outputs.  Every distinct output channel is
        drained exactly once per execution — even when one errors — so
        pipelined executions stay in sync."""
        values: Dict[int, Any] = {}
        first_exc: Optional[BaseException] = None
        seen = set()
        for i in self._output_idxs:
            if i in seen:
                continue
            seen.add(i)
            try:
                data, err = self._output_chans[i].read(timeout=timeout)
            except BaseException as e:  # timeout / closed
                if first_exc is None:
                    first_exc = e
                continue
            if err:
                exc = deserialize_from_bytes(data)
                if not isinstance(exc, BaseException):
                    exc = DAGError(str(exc))
                if first_exc is None:
                    first_exc = exc
            else:
                values[i] = deserialize_from_bytes(data)
        self._pending -= 1
        if first_exc is not None:
            raise first_exc
        outs = [values[i] for i in self._output_idxs]
        return outs if self._multi else outs[0]

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        from ray_tpu.util import flight_recorder as _fr

        for ch in self._channels:
            try:
                ch.close_channel()
            except Exception:
                # Best-effort: a worker that died mid-run already
                # invalidated its channel; count it so leaks show up.
                logger.debug("channel close failed during teardown",
                             exc_info=True)
                _fr.count_suppressed("dag.teardown.close_channel")
        # Loops observe the close and finish; collect their final status.
        import ray_tpu

        for ref in self._loop_refs:
            try:
                ray_tpu.get(ref, timeout=10)
            except Exception as e:
                logger.warning(
                    "DAG worker loop exited abnormally during teardown: %s",
                    e,
                )
                _fr.count_suppressed("dag.teardown.loop_join")
        for ch in self._channels:
            ch.detach()
            ch.unlink()
        self._channels.clear()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            # GC-time teardown: the interpreter may be mid-shutdown, so
            # even logging infrastructure can be gone — swallow, but not
            # silently when the logger still works.
            try:
                logger.debug("teardown from __del__ failed", exc_info=True)
            except Exception:  # raylint: waive[RTL003] interpreter shutdown
                pass


class CompiledDAGRef:
    """Future for one execution (reference: CompiledDAGRef).  Results must be
    consumed in submission order — the pipeline is a static schedule."""

    def __init__(self, dag: CompiledDAG):
        self._dag = dag
        self._done = False
        self._value = None

    def get(self, timeout: Optional[float] = 60.0):
        if not self._done:
            self._value = self._dag._read_result(timeout)
            self._done = True
        return self._value
