"""KV-cache inference path for the Llama family: prefill + ragged decode.

Same design as ``gpt2_decode.py`` (head-major stacked cache
``[L, B, Hkv, T, D]``, scatter writes, Pallas decode-attention kernel) with
the Llama specifics: RMSNorm, rotary positions, SwiGLU, and **grouped-query
attention** — the cache holds only the Hkv kv-heads and the decode kernel
attends each group of H/Hkv query heads against its shared kv-head in one
score tile (the GQA memory win is the whole point of serving Llama-style
models: cache bytes shrink by H/Hkv).

Reference role: the model runner inside the engines the reference wraps
(ray ``python/ray/llm/_internal/serve/engines/vllm/``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, _rmsnorm, rope


def llama_init_cache(cfg: LlamaConfig, batch: int, max_len: int):
    shape = (cfg.n_layer, batch, cfg.n_kv_head, max_len, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def llama_prefill(
    params, tokens, lengths, cache, cfg: LlamaConfig
) -> Tuple[jnp.ndarray, dict]:
    """tokens: [B, S] right-padded prompts; lengths: [B] true lengths.
    Returns (last_logits [B, V], cache with positions [0, S) written)."""
    b, s = tokens.shape
    groups = cfg.n_head // cfg.n_kv_head
    x = params["wte"][tokens].astype(jnp.dtype(cfg.dtype))
    positions = jnp.arange(s, dtype=jnp.int32)
    causal = jnp.tril(jnp.ones((s, s), bool))[None]

    def body(x, layer):
        y = _rmsnorm(x, layer["rms1"], cfg.rms_eps)
        q = jnp.einsum("bse,ehd->bshd", y, layer["wq"])
        k = jnp.einsum("bse,ekd->bskd", y, layer["wk"])
        v = jnp.einsum("bse,ekd->bskd", y, layer["wv"])
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        kr = jnp.repeat(k, groups, axis=2)
        vr = jnp.repeat(v, groups, axis=2)
        scores = jnp.einsum("bshd,bthd->bhst", q, kr).astype(jnp.float32)
        scores = scores / (cfg.head_dim ** 0.5)
        scores = jnp.where(causal[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        o = jnp.einsum("bhst,bthd->bshd", probs, vr)
        x = x + jnp.einsum("bshd,hde->bse", o, layer["wo"]).astype(x.dtype)
        y = _rmsnorm(x, layer["rms2"], cfg.rms_eps)
        gate = jax.nn.silu(jnp.einsum("bse,ef->bsf", y, layer["w_gate"]))
        up = jnp.einsum("bse,ef->bsf", y, layer["w_up"])
        x = x + jnp.einsum(
            "bsf,fe->bse", gate * up, layer["w_down"]
        ).astype(x.dtype)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
    # [L, B, S, Hkv, D] → head-major [L, B, Hkv, S, D].
    ks = ks.transpose(0, 1, 3, 2, 4).astype(cache["k"].dtype)
    vs = vs.transpose(0, 1, 3, 2, 4).astype(cache["v"].dtype)
    cache = {
        "k": jax.lax.dynamic_update_slice(cache["k"], ks, (0, 0, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(cache["v"], vs, (0, 0, 0, 0, 0)),
    }
    x = _rmsnorm(x, params["rms_f"], cfg.rms_eps)
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    logits = jnp.einsum("be,ve->bv", last, params["lm_head"])
    return logits.astype(jnp.float32), cache


def llama_decode_step(
    params, tokens, pos, cache, cfg: LlamaConfig, *, kernel: bool = False
) -> Tuple[jnp.ndarray, dict]:
    """tokens: [B]; pos: [B] position of each token.  Ragged decode with
    per-slot rotary positions."""
    from ..ops.decode_attention import decode_attention

    b = tokens.shape[0]
    x = params["wte"][tokens].astype(jnp.dtype(cfg.dtype))  # [B, E]
    ck, cv = cache["k"], cache["v"]
    new_ks, new_vs = [], []

    for l in range(cfg.n_layer):
        layer = jax.tree.map(lambda a: a[l], params["blocks"])
        y = _rmsnorm(x, layer["rms1"], cfg.rms_eps)
        q = jnp.einsum("be,ehd->bhd", y, layer["wq"])
        k = jnp.einsum("be,ekd->bkd", y, layer["wk"])
        v = jnp.einsum("be,ekd->bkd", y, layer["wv"])
        # rope expects [B, S, H, D]; per-slot positions ride the batch dim.
        q = rope(q[:, None], pos[:, None], cfg.rope_theta)[:, 0]
        k = rope(k[:, None], pos[:, None], cfg.rope_theta)[:, 0]
        new_ks.append(k.astype(ck.dtype))
        new_vs.append(v.astype(cv.dtype))
        # Deferred-scatter protocol (see gpt2_decode.py): cache holds
        # [0, pos-1]; current k/v merged in-kernel, one batched write below.
        o = decode_attention(
            q, ck, cv, pos, l, k_self=new_ks[-1], v_self=new_vs[-1],
            kernel=kernel,
        )  # [B, H, D]
        x = x + jnp.einsum(
            "bhd,hde->be", o.astype(y.dtype), layer["wo"]
        ).astype(x.dtype)
        y = _rmsnorm(x, layer["rms2"], cfg.rms_eps)
        gate = jax.nn.silu(jnp.einsum("be,ef->bf", y, layer["w_gate"]))
        up = jnp.einsum("be,ef->bf", y, layer["w_up"])
        x = x + jnp.einsum(
            "bf,fe->be", gate * up, layer["w_down"]
        ).astype(x.dtype)

    from ..ops.decode_attention import write_token_to_cache

    ck = write_token_to_cache(ck, jnp.stack(new_ks), pos)
    cv = write_token_to_cache(cv, jnp.stack(new_vs), pos)
    x = _rmsnorm(x, params["rms_f"], cfg.rms_eps)
    logits = jnp.einsum("be,ve->bv", x, params["lm_head"])
    return logits.astype(jnp.float32), {"k": ck, "v": cv}
