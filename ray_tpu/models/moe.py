"""Mixture-of-Experts LM with native expert parallelism (EP).

The reference reaches expert parallelism only through vLLM engine config
(SURVEY.md §2.3 — EP delegated to external engines); here EP is a
first-class mesh axis.  GShard/Switch-style top-2 routing with static
shapes throughout:

  - routing is einsum + one_hot + cumsum (no dynamic shapes — XLA-friendly);
  - dispatched token buffers are [experts, batch, capacity, model] with the
    leading axis sharded over the ``expert`` mesh axis; the dispatch and
    combine einsums therefore lower to ``all_to_all`` over ICI;
  - per-expert FFN weights are stacked [n_experts, d_model, d_ff] and
    sharded over (``expert``, -, ``model``), so EP composes with TP;
  - a Switch-style load-balancing aux loss accumulates through the
    ``lax.scan`` over layers.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 50304
    max_seq: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    dtype: str = "bfloat16"
    attention: str = "dense"
    remat: bool = False

    def __post_init__(self):
        # Routing implements top-1 and top-2 (GShard-style second expert);
        # a silently-ignored larger top_k would still inflate capacity().
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {self.top_k}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    def capacity(self, seq_len: int) -> int:
        c = int(self.top_k * seq_len * self.capacity_factor / self.n_experts)
        return max(c, 4)

    @classmethod
    def tiny(cls, **kw) -> "MoEConfig":
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq", 128)
        kw.setdefault("n_layer", 2)
        kw.setdefault("n_head", 4)
        kw.setdefault("d_model", 64)
        kw.setdefault("n_experts", 4)
        return cls(**kw)


def moe_init(key, cfg: MoEConfig):
    e, h, d, L, E = (cfg.d_model, cfg.n_head, cfg.head_dim, cfg.n_layer,
                     cfg.n_experts)
    dt = jnp.dtype(cfg.dtype)
    k = iter(jax.random.split(key, 16))
    init = lambda kk, shape, scale: (jax.random.normal(kk, shape) * scale).astype(dt)
    s = 0.02
    so = s / (2 * L) ** 0.5
    return {
        "wte": init(next(k), (cfg.vocab_size, e), s),
        "wpe": init(next(k), (cfg.max_seq, e), s),
        "blocks": {
            "ln1_g": jnp.ones((L, e), dt),
            "ln1_b": jnp.zeros((L, e), dt),
            "wqkv": init(next(k), (L, e, 3, h, d), s),
            "bqkv": jnp.zeros((L, 3, h, d), dt),
            "wo": init(next(k), (L, h, d, e), so),
            "bo": jnp.zeros((L, e), dt),
            "ln2_g": jnp.ones((L, e), dt),
            "ln2_b": jnp.zeros((L, e), dt),
            # router in f32 for stable softmax over experts
            "wg": (jax.random.normal(next(k), (L, e, E)) * s).astype(jnp.float32),
            "wi": init(next(k), (L, E, e, 4 * e), s),
            "wo2": init(next(k), (L, E, 4 * e, e), so),
        },
        "lnf_g": jnp.ones((e,), dt),
        "lnf_b": jnp.zeros((e,), dt),
    }


def moe_param_axes():
    return {
        # vocab axis unsharded — a vocab-sharded table under the token
        # gather forces SPMD full rematerialization (see gpt2.py).
        "wte": P(None, "embed"),
        "wpe": P(None, "embed"),
        "blocks": {
            "ln1_g": P(None, "norm"),
            "ln1_b": P(None, "norm"),
            "wqkv": P(None, "embed", None, "heads", "kv"),
            "bqkv": P(None, None, "heads", "kv"),
            "wo": P(None, "heads", "kv", "embed"),
            "bo": P(None, "norm"),
            "ln2_g": P(None, "norm"),
            "ln2_b": P(None, "norm"),
            "wg": P(None, "embed", None),
            "wi": P(None, "expert", "embed", "expert_mlp"),
            "wo2": P(None, "expert", "expert_mlp", "embed"),
        },
        "lnf_g": P("norm"),
        "lnf_b": P("norm"),
    }


def _layernorm(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def moe_ffn(x, wg, wi, wo, cfg: MoEConfig, mesh=None):
    """Top-2 routed expert FFN.  x: [B, S, M] → (y [B, S, M], aux_loss).

    Each batch row is a routing group (GShard grouping); the capacity
    cumsum runs over the sequence axis.
    """
    from ..parallel.sharding import with_logical_constraint as wlc

    b, s_len, m = x.shape
    E, C = cfg.n_experts, cfg.capacity(s_len)

    logits = jnp.einsum("bsm,me->bse", x.astype(jnp.float32), wg)
    gates = jax.nn.softmax(logits, axis=-1)  # [B,S,E] f32

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
    # Switch aux loss on the top-1 assignment (fraction × mean prob)
    density = mask1.mean(axis=1)            # [B,E] fraction routed to e
    prob_mean = gates.mean(axis=1)          # [B,E]
    aux = E * jnp.mean(jnp.sum(density * prob_mean, axis=-1))

    pos1 = jnp.cumsum(mask1, axis=1) - mask1      # [B,S,E] queue position
    mask1 = mask1 * (pos1 < C)

    if cfg.top_k >= 2:
        gates2 = gates * (1.0 - jax.nn.one_hot(idx1, E, dtype=jnp.float32))
        idx2 = jnp.argmax(gates2, axis=-1)
        mask2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)
        pos2 = jnp.cumsum(mask2, axis=1) - mask2 + mask1.sum(axis=1, keepdims=True)
        mask2 = mask2 * (pos2 < C)
    else:
        mask2 = jnp.zeros_like(mask1)
        pos2 = jnp.zeros_like(pos1)

    w1 = jnp.sum(gates * mask1, axis=-1)    # [B,S]
    w2 = jnp.sum(gates * mask2, axis=-1)
    denom = w1 + w2 + 1e-9
    w1, w2 = w1 / denom, w2 / denom

    onehot_c1 = jax.nn.one_hot(
        pos1.astype(jnp.int32), C, dtype=jnp.float32) * mask1[..., None]
    onehot_c2 = jax.nn.one_hot(
        pos2.astype(jnp.int32), C, dtype=jnp.float32) * mask2[..., None]
    combine = (w1[..., None, None] * onehot_c1 +
               w2[..., None, None] * onehot_c2)   # [B,S,E,C]
    dispatch = (onehot_c1 + onehot_c2).astype(x.dtype)

    # [B,S,E,C] × [B,S,M] → [E,B,C,M]: lowers to all_to_all (batch-sharded
    # tokens → expert-sharded buffers) when both shardings are annotated.
    xe = jnp.einsum("bsec,bsm->ebcm", dispatch, x)
    xe = wlc(xe, P("expert", "batch", "capacity", None), mesh)
    h = jax.nn.gelu(jnp.einsum("ebcm,emh->ebch", xe, wi))
    h = wlc(h, P("expert", "batch", "capacity", "expert_mlp"), mesh)
    ye = jnp.einsum("ebch,ehm->ebcm", h, wo)
    ye = wlc(ye, P("expert", "batch", "capacity", None), mesh)
    y = jnp.einsum("bsec,ebcm->bsm", combine.astype(ye.dtype), ye)
    return y.astype(x.dtype), aux


def _attention(q, k, v, cfg: MoEConfig, mesh):
    if cfg.attention == "flash":
        from ..ops.attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    from ..ops.attention import reference_attention

    return reference_attention(q, k, v, causal=True)


def _block(x, layer, cfg: MoEConfig, mesh):
    from ..parallel.sharding import with_logical_constraint as wlc

    y = _layernorm(x, layer["ln1_g"], layer["ln1_b"])
    qkv = jnp.einsum("bse,ethd->bsthd", y, layer["wqkv"]) + layer["bqkv"]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    o = _attention(q, k, v, cfg, mesh)
    x = x + (jnp.einsum("bshd,hde->bse", o, layer["wo"]) + layer["bo"]).astype(x.dtype)
    y = _layernorm(x, layer["ln2_g"], layer["ln2_b"])
    ffn, aux = moe_ffn(y, layer["wg"], layer["wi"], layer["wo2"], cfg, mesh)
    x = x + ffn
    return wlc(x, P("batch", "seq", "act_embed"), mesh), aux


def moe_apply(params, tokens, cfg: MoEConfig, mesh=None):
    """tokens: [B, S] int32 → (logits [B, S, V], aux_loss)."""
    from ..parallel.sharding import with_logical_constraint as wlc

    b, s = tokens.shape
    # Replicated-view gather — see gpt2.gpt2_apply for the SPMD rationale.
    wte = wlc(params["wte"], P(None, "act_embed"), mesh)
    x = wte[tokens] + params["wpe"][:s][None]
    x = wlc(x, P("batch", "seq", "act_embed"), mesh)

    block = functools.partial(_block, cfg=cfg, mesh=mesh)
    if cfg.remat:
        block = jax.checkpoint(block)

    def scan_body(x, layer):
        x, aux = block(x, layer)
        return x, aux

    x, auxes = jax.lax.scan(scan_body, x, params["blocks"])
    x = _layernorm(x, params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum("bse,ve->bsv", x, params["wte"])
    return wlc(logits, P("batch", "seq", "vocab"), mesh), jnp.mean(auxes)


def moe_loss(params, tokens, cfg: MoEConfig, mesh=None):
    """Next-token cross-entropy + aux load-balance loss."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = moe_apply(params, inputs, cfg, mesh)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return (logz - gold).mean() + cfg.aux_loss_coef * aux
