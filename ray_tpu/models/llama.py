"""Llama-family decoder LM: RMSNorm + RoPE + GQA + SwiGLU.

The reference serves Llama-class models by delegating to vLLM
(ray ``python/ray/llm/_internal/serve/engines/vllm/vllm_models.py``); here
the architecture is native JAX with the same TPU-first structure as
``gpt2.py``: layer-stacked params applied under ``lax.scan``, logical
sharding axes for DP/FSDP/TP/SP, pluggable attention (dense/flash/ring/
ulysses), optional per-layer remat, bf16 with f32 norm/softmax.

Grouped-query attention shards cleanly on the ``heads`` axis: KV heads are
replicated within a query-head group, so TP on query heads keeps KV local
to the shard (no extra collectives versus MHA).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq: int = 2048
    n_layer: int = 22
    n_head: int = 32
    n_kv_head: int = 8  # GQA: query heads per kv head = n_head // n_kv_head
    d_model: int = 2048
    d_ff: int = 5632
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    attention: str = "dense"  # dense | flash | ring | ulysses
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq", 128)
        kw.setdefault("n_layer", 2)
        kw.setdefault("n_head", 4)
        kw.setdefault("n_kv_head", 2)
        kw.setdefault("d_model", 64)
        kw.setdefault("d_ff", 128)
        return cls(**kw)

    @classmethod
    def tinyllama_1b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)  # defaults above are the 1.1B shape

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        kw.setdefault("n_layer", 32)
        kw.setdefault("n_head", 32)
        kw.setdefault("n_kv_head", 32)
        kw.setdefault("d_model", 4096)
        kw.setdefault("d_ff", 11008)
        kw.setdefault("max_seq", 4096)
        return cls(**kw)


def llama_init(key, cfg: LlamaConfig):
    e, hd = cfg.d_model, cfg.head_dim
    L, H, KV, F = cfg.n_layer, cfg.n_head, cfg.n_kv_head, cfg.d_ff
    dt = jnp.dtype(cfg.dtype)
    k = iter(jax.random.split(key, 12))
    init = lambda kk, shape, scale: (
        jax.random.normal(kk, shape) * scale
    ).astype(dt)
    s = 0.02
    so = s / (2 * L) ** 0.5
    return {
        "wte": init(next(k), (cfg.vocab_size, e), s),
        "blocks": {
            "rms1": jnp.ones((L, e), dt),
            "wq": init(next(k), (L, e, H, hd), s),
            "wk": init(next(k), (L, e, KV, hd), s),
            "wv": init(next(k), (L, e, KV, hd), s),
            "wo": init(next(k), (L, H, hd, e), so),
            "rms2": jnp.ones((L, e), dt),
            "w_gate": init(next(k), (L, e, F), s),
            "w_up": init(next(k), (L, e, F), s),
            "w_down": init(next(k), (L, F, e), so),
        },
        "rms_f": jnp.ones((e,), dt),
        "lm_head": init(next(k), (cfg.vocab_size, e), s),
    }


def llama_param_axes():
    """Logical sharding axes (leading None = layer-stack axis)."""
    return {
        # vocab axis unsharded: the token gather along a vocab-sharded table
        # forces SPMD full rematerialization (see gpt2.py:gpt2_param_axes).
        # lm_head keeps its vocab sharding — it is only ever contracted over
        # embed, producing vocab-sharded logits with no gather.
        "wte": P(None, "embed"),
        "blocks": {
            "rms1": P(None, "norm"),
            "wq": P(None, "embed", "heads", "kv"),
            "wk": P(None, "embed", "heads", "kv"),
            "wv": P(None, "embed", "heads", "kv"),
            "wo": P(None, "heads", "kv", "embed"),
            "rms2": P(None, "norm"),
            "w_gate": P(None, "embed", "mlp"),
            "w_up": P(None, "embed", "mlp"),
            "w_down": P(None, "mlp", "embed"),
        },
        "rms_f": P("norm"),
        "lm_head": P("vocab", "embed"),
    }


def _rmsnorm(x, g, eps: float):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    return (x32 * scale * g.astype(jnp.float32)).astype(x.dtype)


def rope(x, positions, theta: float):
    """Rotary embedding.  x: [B, S, H, D]; positions: [B, S] or [S]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    if positions.ndim == 1:
        positions = positions[None]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,S,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    y1 = x1 * cos - x2 * sin
    y2 = x1 * sin + x2 * cos
    out = jnp.stack([y1, y2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig, mesh):
    if cfg.attention == "flash":
        from ..ops.attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    if cfg.attention == "ring":
        from ..parallel.ring_attention import ring_attention

        assert mesh is not None, "ring attention requires a mesh"
        return ring_attention(q, k, v, mesh, causal=True)
    if cfg.attention == "ulysses":
        from ..parallel.ulysses import ulysses_attention

        assert mesh is not None, "ulysses attention requires a mesh"
        return ulysses_attention(q, k, v, mesh, causal=True)
    from ..ops.attention import reference_attention

    return reference_attention(q, k, v, causal=True)


def _block(x, layer, positions, cfg: LlamaConfig, mesh):
    from ..parallel.sharding import with_logical_constraint as wlc

    groups = cfg.n_head // cfg.n_kv_head
    y = _rmsnorm(x, layer["rms1"], cfg.rms_eps)
    q = jnp.einsum("bse,ehd->bshd", y, layer["wq"])
    k = jnp.einsum("bse,ekd->bskd", y, layer["wk"])
    v = jnp.einsum("bse,ekd->bskd", y, layer["wv"])
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    # GQA: repeat kv heads across their query-head group.
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    q = wlc(q, P("batch", "seq", "heads", "kv"), mesh)
    k = wlc(k, P("batch", "seq", "heads", "kv"), mesh)
    v = wlc(v, P("batch", "seq", "heads", "kv"), mesh)
    o = _attention(q, k, v, cfg, mesh)
    x = x + jnp.einsum("bshd,hde->bse", o, layer["wo"]).astype(x.dtype)
    y = _rmsnorm(x, layer["rms2"], cfg.rms_eps)
    gate = jax.nn.silu(jnp.einsum("bse,ef->bsf", y, layer["w_gate"]))
    up = jnp.einsum("bse,ef->bsf", y, layer["w_up"])
    h = wlc(gate * up, P("batch", "seq", "mlp"), mesh)
    x = x + jnp.einsum("bsf,fe->bse", h, layer["w_down"]).astype(x.dtype)
    return wlc(x, P("batch", "seq", "act_embed"), mesh)


def llama_apply(params, tokens, cfg: LlamaConfig, mesh=None):
    """tokens: [B, S] int32 → logits [B, S, V]."""
    from ..parallel.sharding import with_logical_constraint as wlc

    b, s = tokens.shape
    # Replicated-view gather — see gpt2.gpt2_apply for the SPMD rationale.
    wte = wlc(params["wte"], P(None, "act_embed"), mesh)
    x = wte[tokens].astype(jnp.dtype(cfg.dtype))
    x = wlc(x, P("batch", "seq", "act_embed"), mesh)
    positions = jnp.arange(s, dtype=jnp.int32)

    block = functools.partial(_block, positions=positions, cfg=cfg, mesh=mesh)
    if cfg.remat:
        block = jax.checkpoint(block)

    def scan_body(x, layer):
        return block(x, layer), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    x = _rmsnorm(x, params["rms_f"], cfg.rms_eps)
    logits = jnp.einsum("bse,ve->bsv", x, params["lm_head"])
    return wlc(logits, P("batch", "seq", "vocab"), mesh)


def llama_loss(params, tokens, cfg: LlamaConfig, mesh=None,
               z_loss: float = 0.0):
    """Next-token cross-entropy; tokens [B, S+1]."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = llama_apply(params, inputs, cfg, mesh).astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold).mean()
    if z_loss > 0:
        nll = nll + z_loss * (logz ** 2).mean()
    return nll
