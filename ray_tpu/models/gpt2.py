"""GPT-2 family — the flagship LM (BASELINE.md north-star config #4:
GPT-2-medium LM with streaming data + sharded optimizer).

TPU-first design decisions:
  - plain-JAX pytree params with *logical* sharding axes
    (``gpt2_param_axes``) mapped through ``ray_tpu.parallel.sharding`` rules
    — the same model runs DP, FSDP, TP, and SP by changing the rule table;
  - layers are stacked on a leading axis and applied with ``lax.scan``
    (one trace/compile regardless of depth; XLA pipelines the layer loop);
  - attention is pluggable: dense (XLA-fused), Pallas flash kernel, ring
    (context parallel over ``seq`` axis), or Ulysses all-to-all;
  - ``remat=True`` wraps each layer in ``jax.checkpoint`` to trade FLOPs
    for HBM;
  - bf16 activations/params with f32 layernorm + softmax accumulation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304  # 50257 padded up for lane tiling
    max_seq: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    dtype: str = "bfloat16"
    attention: str = "dense"  # dense | flash | ring | ulysses
    remat: bool = False
    # "full" recomputes the whole block in backward (measured FASTEST on
    # bandwidth-poor parts — storing activations costs more than
    # recomputing them); "dots" = jax dots_with_no_batch_dims_saveable
    # (saves nothing for our batched einsums — degenerates to full);
    # "dots_all" saves every contraction result (dots_saveable);
    # "matmuls" saves the tagged projection outputs + attention residual;
    # "save_mlp" saves only the tagged MLP hidden activations.  Unknown
    # values fall through to "full".
    remat_policy: str = "full"  # full | dots | dots_all | matmuls | save_mlp

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @classmethod
    def medium(cls, **kw) -> "GPT2Config":
        return cls(n_layer=24, n_head=16, d_model=1024, **kw)

    @classmethod
    def small(cls, **kw) -> "GPT2Config":
        return cls(n_layer=12, n_head=12, d_model=768, **kw)

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        kw.setdefault("vocab_size", 512)
        kw.setdefault("max_seq", 128)
        return cls(n_layer=2, n_head=4, d_model=64, **kw)


def gpt2_init(key, cfg: GPT2Config):
    e, h, d, L = cfg.d_model, cfg.n_head, cfg.head_dim, cfg.n_layer
    k = iter(jax.random.split(key, 16))
    dt = jnp.dtype(cfg.dtype)
    init = lambda kk, shape, scale: (jax.random.normal(kk, shape) * scale).astype(dt)
    s = 0.02
    so = s / (2 * L) ** 0.5  # gpt-2 residual-out scaling
    params = {
        "wte": init(next(k), (cfg.vocab_size, e), s),
        "wpe": init(next(k), (cfg.max_seq, e), s),
        "blocks": {
            "ln1_g": jnp.ones((L, e), dt),
            "ln1_b": jnp.zeros((L, e), dt),
            "wqkv": init(next(k), (L, e, 3, h, d), s),
            "bqkv": jnp.zeros((L, 3, h, d), dt),
            "wo": init(next(k), (L, h, d, e), so),
            "bo": jnp.zeros((L, e), dt),
            "ln2_g": jnp.ones((L, e), dt),
            "ln2_b": jnp.zeros((L, e), dt),
            "wi": init(next(k), (L, e, 4 * e), s),
            "bi": jnp.zeros((L, 4 * e), dt),
            "wo2": init(next(k), (L, 4 * e, e), so),
            "bo2": jnp.zeros((L, e), dt),
        },
        "lnf_g": jnp.ones((e,), dt),
        "lnf_b": jnp.zeros((e,), dt),
    }
    return params


def gpt2_param_axes():
    """Logical sharding axes per parameter (leading None = layer-stack axis)."""
    return {
        # NOTE: the vocab axis of the embedding table is deliberately NOT
        # sharded: ``wte[tokens]`` gathers along it, and a vocab-sharded
        # table forces XLA SPMD into "involuntary full rematerialization"
        # (replicate-then-repartition) on every step.  Sharding embed over
        # fsdp keeps the ZeRO-3 memory win; the unembedding matmul still
        # produces vocab(model)-sharded logits by slicing.
        "wte": P(None, "embed"),
        "wpe": P(None, "embed"),
        "blocks": {
            "ln1_g": P(None, "norm"),
            "ln1_b": P(None, "norm"),
            "wqkv": P(None, "embed", None, "heads", "kv"),
            "bqkv": P(None, None, "heads", "kv"),
            "wo": P(None, "heads", "kv", "embed"),
            "bo": P(None, "norm"),
            "ln2_g": P(None, "norm"),
            "ln2_b": P(None, "norm"),
            "wi": P(None, "embed", "mlp"),
            "bi": P(None, "mlp"),
            "wo2": P(None, "mlp", "embed"),
            "bo2": P(None, "norm"),
        },
        "lnf_g": P("norm"),
        "lnf_b": P("norm"),
    }


def _layernorm(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def _attention(q, k, v, cfg: GPT2Config, mesh):
    if cfg.attention == "dense_remat":
        # Dense XLA attention (fastest at moderate S on this chip: the
        # einsum-softmax fusion runs at the matmul roofline) with
        # ``jax.checkpoint`` so the [B,H,S,S] probs are recomputed in
        # backward instead of stored — flash-attention's memory profile at
        # dense-attention speed.  Long S still wants the Pallas kernel.
        from ..ops.attention import reference_attention

        return jax.checkpoint(
            lambda q, k, v: reference_attention(q, k, v, causal=True)
        )(q, k, v)
    if cfg.attention == "flash":
        from ..ops.attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    if cfg.attention == "ring":
        from ..parallel.ring_attention import ring_attention

        assert mesh is not None, "ring attention requires a mesh"
        return ring_attention(q, k, v, mesh, causal=True)
    if cfg.attention == "ulysses":
        from ..parallel.ulysses import ulysses_attention

        assert mesh is not None, "ulysses attention requires a mesh"
        return ulysses_attention(q, k, v, mesh, causal=True)
    from ..ops.attention import reference_attention

    return reference_attention(q, k, v, causal=True)


def _block(x, layer, cfg: GPT2Config, mesh):
    from ..parallel.sharding import with_logical_constraint as wlc

    b, s, e = x.shape
    h, d = cfg.n_head, cfg.head_dim
    # checkpoint_name tags (no-ops outside a names-based remat policy):
    # "matmuls" saves every projection output so backward recomputes only
    # the cheap elementwise chains (LN/gelu/residual) — the sweet spot
    # between full remat (recompute a whole forward, ~8/6 executed FLOPs)
    # and no remat (stored-activation reads dominate a bandwidth-poor bwd).
    from jax.ad_checkpoint import checkpoint_name as _ckpt_name

    y = _layernorm(x, layer["ln1_g"], layer["ln1_b"])
    qkv = jnp.einsum("bse,ethd->bsthd", y, layer["wqkv"]) + layer["bqkv"]
    qkv = _ckpt_name(qkv, "qkv")
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = wlc(q, P("batch", "seq", "heads", "kv"), mesh)
    k = wlc(k, P("batch", "seq", "heads", "kv"), mesh)
    v = wlc(v, P("batch", "seq", "heads", "kv"), mesh)
    o = _attention(q, k, v, cfg, mesh)
    o = _ckpt_name(o, "attn_out")
    x = x + (jnp.einsum("bshd,hde->bse", o, layer["wo"]) + layer["bo"]).astype(x.dtype)
    x = _ckpt_name(x, "attn_resid")
    y = _layernorm(x, layer["ln2_g"], layer["ln2_b"])
    hdn = jax.nn.gelu(jnp.einsum("bse,ef->bsf", y, layer["wi"]) + layer["bi"])
    hdn = _ckpt_name(hdn, "mlp_hidden")
    hdn = wlc(hdn, P("batch", "seq", "mlp"), mesh)
    x = x + (jnp.einsum("bsf,fe->bse", hdn, layer["wo2"]) + layer["bo2"]).astype(x.dtype)
    return wlc(x, P("batch", "seq", "act_embed"), mesh)


def gpt2_hidden(params, tokens, cfg: GPT2Config, mesh=None):
    """tokens: [B, S] int32 → final layernormed hidden states [B, S, E]."""
    from ..parallel.sharding import with_logical_constraint as wlc

    b, s = tokens.shape
    # Gather from an explicitly replicated view of the table: the ZeRO-3
    # all-gather of wte happens as one clean collective, the token gather
    # then has a replicated operand and output, and the batch/seq constraint
    # below is a free slice.  Gathering from the fsdp-sharded table instead
    # makes SPMD reshard the gather output embed→batch, which it can only do
    # by full rematerialization (round-1 MULTICHIP finding).
    wte = wlc(params["wte"], P(None, "act_embed"), mesh)
    x = wte[tokens] + params["wpe"][:s][None]
    x = wlc(x, P("batch", "seq", "act_embed"), mesh)

    block = functools.partial(_block, cfg=cfg, mesh=mesh)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        elif cfg.remat_policy == "dots_all":
            # Save EVERY contraction result (batched included — our
            # einsums all carry a batch dim, so the no-batch-dims variant
            # saves nothing and degenerates to full remat).  Backward then
            # recomputes only elementwise chains (LN/gelu/residual): a few
            # percent of executed FLOPs instead of a full second forward.
            block = jax.checkpoint(
                block, policy=jax.checkpoint_policies.dots_saveable
            )
        elif cfg.remat_policy == "matmuls":
            # Save the tagged projection outputs (+ the attention-branch
            # residual so bwd needn't replay attention to rebuild the MLP
            # branch input); recompute only elementwise chains.
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "qkv", "attn_out", "attn_resid", "mlp_hidden"
                ),
            )
        elif cfg.remat_policy == "save_mlp":
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "mlp_hidden"
                ),
            )
        else:
            block = jax.checkpoint(block)

    def scan_body(x, layer):
        return block(x, layer), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    return _layernorm(x, params["lnf_g"], params["lnf_b"])


def gpt2_apply(params, tokens, cfg: GPT2Config, mesh=None):
    """tokens: [B, S] int32 → logits [B, S, V]."""
    from ..parallel.sharding import with_logical_constraint as wlc

    x = gpt2_hidden(params, tokens, cfg, mesh)
    logits = jnp.einsum("bse,ve->bsv", x, params["wte"])
    return wlc(logits, P("batch", "seq", "vocab"), mesh)


def _ce_from_logits(logits, targets, z_loss: float):
    """Summed (not mean) next-token NLL with f32 reduction arithmetic fused
    into the bf16 logits (no f32 [.., V] materialization)."""
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold.astype(jnp.float32)).sum()
    if z_loss > 0:
        nll = nll + z_loss * (logz ** 2).sum()
    return nll


def gpt2_loss(
    params, tokens, cfg: GPT2Config, mesh=None, z_loss: float = 0.0,
    ce_chunks: int = 0,
):
    """Next-token cross-entropy.  tokens: [B, S+1] (inputs = [:, :-1]).

    ``ce_chunks > 0`` evaluates the unembedding + CE in that many
    rematerialized sequence chunks: peak memory holds one [B, S/c, V]
    logits block instead of [B, S, V] (the classic blockwise-CE recipe;
    the unembed matmul is recomputed chunkwise in backward).  This is what
    lets the single-chip train batch double on a 16G-HBM chip.
    """
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    x = gpt2_hidden(params, inputs, cfg, mesh)
    b, s, e = x.shape
    if ce_chunks > 1 and s % ce_chunks != 0:
        raise ValueError(
            f"ce_chunks={ce_chunks} must divide the sequence length {s} "
            "(silently falling back would materialize the full [B,S,V] "
            "logits the caller asked to avoid)"
        )
    if ce_chunks <= 1:
        logits = jnp.einsum("bse,ve->bsv", x, params["wte"])
        from ..parallel.sharding import with_logical_constraint as wlc

        logits = wlc(logits, P("batch", "seq", "vocab"), mesh)
        return _ce_from_logits(logits, targets, z_loss) / (b * s)

    c = s // ce_chunks
    xs = x.reshape(b, ce_chunks, c, e).swapaxes(0, 1)  # [n, B, C, E]
    ts = targets.reshape(b, ce_chunks, c).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_nll(wte, x_c, t_c):
        logits = jnp.einsum("bce,ve->bcv", x_c, wte)
        return _ce_from_logits(logits, t_c, z_loss)

    def body(acc, xt):
        x_c, t_c = xt
        return acc + chunk_nll(params["wte"], x_c, t_c), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xs, ts))
    return total / (b * s)
