"""Minimal MLP (the fashion-MNIST / smoke-test model; reference workload:
BASELINE.md north-star config #1).  Plain-JAX pytree params."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def mlp_init(key, sizes: Sequence[int]):
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (fan_in, fan_out)) * (2.0 / fan_in) ** 0.5
        params.append({"w": w, "b": jnp.zeros((fan_out,))})
    return params


def mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x
