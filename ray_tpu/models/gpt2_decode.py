"""KV-cache inference path for GPT-2: prefill + single-token decode.

The serving analog of the training forward in ``gpt2.py`` (reference role:
the model runner inside the vLLM engine the reference wraps, ray
``python/ray/llm/_internal/serve/engines/vllm/``).  TPU-first decisions:

  - the KV cache is a pair of layer-stacked **head-major** arrays
    ``[L, B, H, T_max, D]`` living in HBM across steps — this layout means
    neither prefill writes, decode reads, nor the decode-attention kernel
    ever transpose the cache on the hot path;
  - cache writes are **deferred**: each layer's current-token k/v is merged
    into attention analytically (``k_self``/``v_self`` in
    ``ops/decode_attention.py``) and all 2L writes collapse into one
    batched ``write_token_to_cache`` at the end of the step — TPU scatters
    with multiple index dims lower pathologically (~1 ms each), so this is
    worth ~20 ms/step at L=12 (round-1 design: 36 ms/step; this: 20.5 ms
    at B=32, T=1024 on the v5e-lite part, whose effective HBM bandwidth of
    ~40-60 GB/s — not compute — is the decode floor);
  - per-slot positions make the batch *ragged*: each sequence attends only
    to its own ``[0, pos]`` prefix;
  - the layer loop is a Python loop (static layer indices; L compile-time
    bodies are fine for decoders).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from .gpt2 import GPT2Config, _layernorm


def gpt2_init_cache(cfg: GPT2Config, batch: int, max_len: int):
    shape = (cfg.n_layer, batch, cfg.n_head, max_len, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _qkv(x, layer):
    qkv = jnp.einsum("bse,ethd->bsthd", x, layer["wqkv"]) + layer["bqkv"]
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]


def _masked_attention(q, k, v, mask):
    """q [B,S,H,D] over k/v [B,S,H,D] with bool mask [B,S,S]."""
    scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
    scores = scores / (q.shape[-1] ** 0.5)
    scores = jnp.where(mask[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def gpt2_prefill(
    params, tokens, lengths, cache, cfg: GPT2Config
) -> Tuple[jnp.ndarray, dict]:
    """Run the prompt through the model, filling the cache.

    tokens: [B, S] right-padded prompts; lengths: [B] true lengths.
    Returns (last_logits [B, V], cache with positions [0, S) written).
    """
    b, s = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:s][None]
    x = x.astype(jnp.dtype(cfg.dtype))
    causal = jnp.tril(jnp.ones((s, s), bool))[None]  # [1, S, S]

    def body(x, layer):
        y = _layernorm(x, layer["ln1_g"], layer["ln1_b"])
        q, k, v = _qkv(y, layer)
        o = _masked_attention(q, k, v, causal)
        x = x + (
            jnp.einsum("bshd,hde->bse", o, layer["wo"]) + layer["bo"]
        ).astype(x.dtype)
        y = _layernorm(x, layer["ln2_g"], layer["ln2_b"])
        h = jax.nn.gelu(jnp.einsum("bse,ef->bsf", y, layer["wi"]) + layer["bi"])
        x = x + (
            jnp.einsum("bsf,fe->bse", h, layer["wo2"]) + layer["bo2"]
        ).astype(x.dtype)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
    # ks/vs: [L, B, S, H, D] → head-major [L, B, H, S, D].
    ks = ks.transpose(0, 1, 3, 2, 4).astype(cache["k"].dtype)
    vs = vs.transpose(0, 1, 3, 2, 4).astype(cache["v"].dtype)
    cache = {
        "k": jax.lax.dynamic_update_slice(cache["k"], ks, (0, 0, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(cache["v"], vs, (0, 0, 0, 0, 0)),
    }
    x = _layernorm(x, params["lnf_g"], params["lnf_b"])
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    logits = jnp.einsum("be,ve->bv", last, params["wte"])
    return logits.astype(jnp.float32), cache


def gpt2_decode_step(
    params, tokens, pos, cache, cfg: GPT2Config, *, kernel: bool = False
) -> Tuple[jnp.ndarray, dict]:
    """One generation step for a ragged batch.

    tokens: [B] the most recent token per slot; pos: [B] its position.
    Writes k/v at ``pos`` and attends each slot to its own ``[0, pos]``.
    Returns (logits [B, V], updated cache).

    ``kernel=False`` (default) uses the XLA decode attention: on the
    bandwidth-limited v5e-lite part the fused einsum path measures 20.5 ms
    vs 29 ms for the Pallas kernel at B=32/T=1024 (the kernel's per-program
    full-T block copies can't ride the ~40 GB/s effective HBM).  The kernel
    remains the right call on full-bandwidth parts / long caches.
    """
    from ..ops.decode_attention import decode_attention

    b = tokens.shape[0]
    x = params["wte"][tokens] + params["wpe"][pos]
    x = x.astype(jnp.dtype(cfg.dtype))  # [B, E]
    ck, cv = cache["k"], cache["v"]
    new_ks, new_vs = [], []

    for l in range(cfg.n_layer):
        layer = jax.tree.map(lambda a: a[l], params["blocks"])
        y = _layernorm(x, layer["ln1_g"], layer["ln1_b"])
        qkv = jnp.einsum("be,ethd->bthd", y, layer["wqkv"]) + layer["bqkv"]
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, H, D]
        new_ks.append(k.astype(ck.dtype))
        new_vs.append(v.astype(cv.dtype))
        # Deferred-scatter protocol: the cache holds [0, pos-1]; the current
        # token's k/v are merged in-kernel (one batched cache write below
        # replaces 2L per-layer scatters — TPU scatters cost ~1 ms each).
        o = decode_attention(
            q, ck, cv, pos, l, k_self=new_ks[-1], v_self=new_vs[-1],
            kernel=kernel,
        )  # [B, H, D]
        x = x + (
            jnp.einsum("bhd,hde->be", o.astype(y.dtype), layer["wo"])
            + layer["bo"]
        ).astype(x.dtype)
        y = _layernorm(x, layer["ln2_g"], layer["ln2_b"])
        h = jax.nn.gelu(jnp.einsum("be,ef->bf", y, layer["wi"]) + layer["bi"])
        x = x + (
            jnp.einsum("bf,fe->be", h, layer["wo2"]) + layer["bo2"]
        ).astype(x.dtype)

    from ..ops.decode_attention import write_token_to_cache

    ck = write_token_to_cache(ck, jnp.stack(new_ks), pos)
    cv = write_token_to_cache(cv, jnp.stack(new_vs), pos)
    x = _layernorm(x, params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum("be,ve->bv", x, params["wte"])
    return logits.astype(jnp.float32), {"k": ck, "v": cv}


def gpt2_decode_multi(
    params, tokens, pos, cache, cfg: GPT2Config, n_steps: int,
    *, kernel: bool = False,
):
    """Multi-step greedy decode: ``n_steps`` tokens per dispatch via
    ``lax.scan`` with the argmax fused in-graph (vLLM-style multi-step
    scheduling).  On a remote-dispatch backend this amortizes the per-call
    launch latency across n_steps tokens — the single-step loop pays ~2
    host round trips per token.

    Continuous-batching engines call this between admission points: new
    requests join slots only at chunk boundaries.  Returns
    (tokens_out [n_steps, B], next_tokens [B], next_pos [B], cache).
    """

    def body(carry, _):
        toks, p, c = carry
        logits, c = gpt2_decode_step(params, toks, p, c, cfg, kernel=kernel)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, p + 1, c), nxt

    (nxt, next_pos, cache), out = jax.lax.scan(
        body, (tokens, pos, cache), None, length=n_steps
    )
    return out, nxt, next_pos, cache


def sample_logits(logits, key, temperature, top_k: int = 0, top_p: float = 1.0):
    """Temperature / top-k / top-p sampling on [B, V] logits (greedy when
    temperature == 0)."""
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temperature, 1e-6)
    scaled = logits / temp
    if top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    if top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest set with cumulative prob >= top_p; find the cutoff logit.
        cutoff_idx = jnp.argmax(cum >= top_p, axis=-1)
        cutoff = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1
        )
        scaled = jnp.where(scaled < cutoff, -1e30, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled)
