"""KV-cache inference path for GPT-2: prefill + single-token decode.

The serving analog of the training forward in ``gpt2.py`` (reference role:
the model runner inside the vLLM engine the reference wraps, ray
``python/ray/llm/_internal/serve/engines/vllm/``).  TPU-first decisions:
  - the KV cache is a pair of layer-stacked arrays ``[L, B, S_max, H, D]``
    living in HBM across steps; decode updates them with
    ``dynamic_update_slice`` (XLA keeps the update in place under jit
    donation);
  - both phases scan over the layer axis (one compile regardless of depth);
  - per-slot positions make the batch *ragged*: each sequence attends only
    to its own ``[0, pos]`` prefix, so one jitted decode step serves a
    continuous batch of requests at different generation offsets.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from .gpt2 import GPT2Config, _layernorm


def gpt2_init_cache(cfg: GPT2Config, batch: int, max_len: int):
    shape = (cfg.n_layer, batch, max_len, cfg.n_head, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _qkv(x, layer):
    qkv = jnp.einsum("bse,ethd->bsthd", x, layer["wqkv"]) + layer["bqkv"]
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]


def _masked_attention(q, k, v, mask):
    """q [B,S,H,D] over k/v [B,T,H,D] with additive bool mask [B,S,T]."""
    scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32)
    scores = scores / (q.shape[-1] ** 0.5)
    scores = jnp.where(mask[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def gpt2_prefill(
    params, tokens, lengths, cache, cfg: GPT2Config
) -> Tuple[jnp.ndarray, dict]:
    """Run the prompt through the model, filling the cache.

    tokens: [B, S] right-padded prompts; lengths: [B] true lengths.
    Returns (last_logits [B, V], cache with positions [0, S) written).
    """
    b, s = tokens.shape
    x = params["wte"][tokens] + params["wpe"][:s][None]
    x = x.astype(jnp.dtype(cfg.dtype))
    causal = jnp.tril(jnp.ones((s, s), bool))[None]  # [1, S, S]

    def body(x, layer):
        y = _layernorm(x, layer["ln1_g"], layer["ln1_b"])
        q, k, v = _qkv(y, layer)
        o = _masked_attention(q, k, v, causal)
        x = x + (
            jnp.einsum("bshd,hde->bse", o, layer["wo"]) + layer["bo"]
        ).astype(x.dtype)
        y = _layernorm(x, layer["ln2_g"], layer["ln2_b"])
        h = jax.nn.gelu(jnp.einsum("bse,ef->bsf", y, layer["wi"]) + layer["bi"])
        x = x + (
            jnp.einsum("bsf,fe->bse", h, layer["wo2"]) + layer["bo2"]
        ).astype(x.dtype)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], ks.astype(cache["k"].dtype), (0, 0, 0, 0, 0)
        ),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], vs.astype(cache["v"].dtype), (0, 0, 0, 0, 0)
        ),
    }
    x = _layernorm(x, params["lnf_g"], params["lnf_b"])
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]
    logits = jnp.einsum("be,ve->bv", last, params["wte"])
    return logits.astype(jnp.float32), cache


def gpt2_decode_step(
    params, tokens, pos, cache, cfg: GPT2Config
) -> Tuple[jnp.ndarray, dict]:
    """One generation step for a ragged batch.

    tokens: [B] the most recent token per slot; pos: [B] its position.
    Writes k/v at ``pos`` and attends each slot to its own ``[0, pos]``.
    Returns (logits [B, V], updated cache).
    """
    b = tokens.shape[0]
    t_max = cache["k"].shape[2]
    x = params["wte"][tokens] + params["wpe"][pos]
    x = x.astype(jnp.dtype(cfg.dtype))[:, None]  # [B, 1, E]
    # [B, 1, T] — slot b attends to cache positions <= pos[b].
    mask = (jnp.arange(t_max)[None] <= pos[:, None])[:, None]
    batch_idx = jnp.arange(b)

    def body(x, inputs):
        layer, k_l, v_l = inputs
        y = _layernorm(x, layer["ln1_g"], layer["ln1_b"])
        q, k, v = _qkv(y, layer)  # [B, 1, H, D]
        k_l = k_l.at[batch_idx, pos].set(k[:, 0].astype(k_l.dtype))
        v_l = v_l.at[batch_idx, pos].set(v[:, 0].astype(v_l.dtype))
        o = _masked_attention(q, k_l, v_l, mask)
        x = x + (
            jnp.einsum("bshd,hde->bse", o, layer["wo"]) + layer["bo"]
        ).astype(x.dtype)
        y = _layernorm(x, layer["ln2_g"], layer["ln2_b"])
        h = jax.nn.gelu(jnp.einsum("bse,ef->bsf", y, layer["wi"]) + layer["bi"])
        x = x + (
            jnp.einsum("bsf,fe->bse", h, layer["wo2"]) + layer["bo2"]
        ).astype(x.dtype)
        return x, (k_l, v_l)

    x, (ks, vs) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"])
    )
    cache = {"k": ks, "v": vs}
    x = _layernorm(x[:, 0], params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum("be,ve->bv", x, params["wte"])
    return logits.astype(jnp.float32), cache


def sample_logits(logits, key, temperature, top_k: int = 0, top_p: float = 1.0):
    """Temperature / top-k / top-p sampling on [B, V] logits (greedy when
    temperature == 0)."""
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temperature, 1e-6)
    scaled = logits / temp
    if top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    if top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest set with cumulative prob >= top_p; find the cutoff logit.
        cutoff_idx = jnp.argmax(cum >= top_p, axis=-1)
        cutoff = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1
        )
        scaled = jnp.where(scaled < cutoff, -1e30, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled)
