from .gpt2 import GPT2Config, gpt2_apply, gpt2_init, gpt2_loss, gpt2_param_axes  # noqa: F401
from .mlp import mlp_apply, mlp_init  # noqa: F401
