import dataclasses as _dataclasses
from typing import Any as _Any, Callable as _Callable

from .gpt2 import (  # noqa: F401
    GPT2Config,
    gpt2_apply,
    gpt2_hidden,
    gpt2_init,
    gpt2_loss,
    gpt2_param_axes,
)
from .gpt2_decode import (  # noqa: F401
    gpt2_decode_step,
    gpt2_init_cache,
    gpt2_prefill,
    sample_logits,
)
from .llama import (  # noqa: F401
    LlamaConfig,
    llama_apply,
    llama_init,
    llama_loss,
    llama_param_axes,
)
from .llama_decode import (  # noqa: F401
    llama_decode_step,
    llama_init_cache,
    llama_prefill,
)


@_dataclasses.dataclass(frozen=True)
class ModelFamily:
    """Uniform train + serve surface over a model architecture — what makes
    the LLM engine model-agnostic (round-1 finding: the engine was
    hard-wired to GPT-2 while llama sat unused; reference analog: vLLM's
    model registry consumed by ray's engine wrapper,
    ``python/ray/llm/_internal/serve/engines/vllm/vllm_models.py``)."""

    name: str
    init: _Callable  # (key, cfg) -> params
    apply: _Callable  # (params, tokens, cfg, mesh=None) -> logits
    loss: _Callable  # (params, tokens, cfg, mesh=None, ...) -> scalar
    param_axes: _Callable  # () -> logical sharding tree
    init_cache: _Callable  # (cfg, batch, max_len) -> cache
    prefill: _Callable  # (params, tokens, lengths, cache, cfg)
    decode_step: _Callable  # (params, tokens, pos, cache, cfg)


_FAMILIES = {}


def register_model_family(config_cls, family: ModelFamily) -> None:
    _FAMILIES[config_cls] = family


def model_family(cfg: _Any) -> ModelFamily:
    """Resolve the ModelFamily for a model config instance."""
    for cls, fam in _FAMILIES.items():
        if isinstance(cfg, cls):
            return fam
    raise TypeError(
        f"no registered model family for config type {type(cfg).__name__}"
    )
from .mlp import mlp_apply, mlp_init  # noqa: F401
from .moe import (  # noqa: F401
    MoEConfig,
    moe_apply,
    moe_ffn,
    moe_init,
    moe_loss,
    moe_param_axes,
)
from .resnet import (  # noqa: F401
    ResNetConfig,
    resnet_apply,
    resnet_init,
    resnet_loss,
    resnet_param_axes,
)
from .vit import ViTConfig, vit_apply, vit_init, vit_loss, vit_param_axes  # noqa: F401


register_model_family(
    GPT2Config,
    ModelFamily(
        name="gpt2",
        init=gpt2_init,
        apply=gpt2_apply,
        loss=gpt2_loss,
        param_axes=gpt2_param_axes,
        init_cache=gpt2_init_cache,
        prefill=gpt2_prefill,
        decode_step=gpt2_decode_step,
    ),
)
register_model_family(
    LlamaConfig,
    ModelFamily(
        name="llama",
        init=llama_init,
        apply=llama_apply,
        loss=llama_loss,
        param_axes=llama_param_axes,
        init_cache=llama_init_cache,
        prefill=llama_prefill,
        decode_step=llama_decode_step,
    ),
)
