from .gpt2 import GPT2Config, gpt2_apply, gpt2_init, gpt2_loss, gpt2_param_axes  # noqa: F401
from .gpt2_decode import (  # noqa: F401
    gpt2_decode_step,
    gpt2_init_cache,
    gpt2_prefill,
    sample_logits,
)
from .llama import (  # noqa: F401
    LlamaConfig,
    llama_apply,
    llama_init,
    llama_loss,
    llama_param_axes,
)
from .mlp import mlp_apply, mlp_init  # noqa: F401
from .moe import (  # noqa: F401
    MoEConfig,
    moe_apply,
    moe_ffn,
    moe_init,
    moe_loss,
    moe_param_axes,
)
from .resnet import (  # noqa: F401
    ResNetConfig,
    resnet_apply,
    resnet_init,
    resnet_loss,
    resnet_param_axes,
)
from .vit import ViTConfig, vit_apply, vit_init, vit_loss, vit_param_axes  # noqa: F401
