"""ViT (Vision Transformer) — BASELINE.md north-star config #5:
ViT-B/16 batch inference on TPU-chip Serve replicas.

TPU-first: patchify is a single reshape+matmul (keeps the MXU busy instead
of an im2col conv), the encoder stack is ``lax.scan`` over stacked layer
params (one compile for any depth), attention is pluggable through
``ray_tpu.ops.attention``, and params carry logical sharding axes so the
same model runs replicated (Serve replicas) or TP/FSDP-sharded (Train).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    mlp_dim: int = 3072
    num_classes: int = 1000
    dtype: str = "bfloat16"
    attention: str = "dense"  # dense | flash

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @classmethod
    def b16(cls, **kw) -> "ViTConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("n_layer", 2)
        kw.setdefault("n_head", 4)
        kw.setdefault("d_model", 64)
        kw.setdefault("mlp_dim", 128)
        kw.setdefault("num_classes", 10)
        return cls(**kw)


def vit_init(key, cfg: ViTConfig):
    e, h, d, L = cfg.d_model, cfg.n_head, cfg.head_dim, cfg.n_layer
    patch_dim = cfg.patch_size * cfg.patch_size * 3
    dt = jnp.dtype(cfg.dtype)
    k = iter(jax.random.split(key, 16))
    init = lambda kk, shape, scale: (jax.random.normal(kk, shape) * scale).astype(dt)
    s = 0.02
    return {
        "patch_w": init(next(k), (patch_dim, e), (1.0 / patch_dim) ** 0.5),
        "patch_b": jnp.zeros((e,), dt),
        "cls": jnp.zeros((1, 1, e), dt),
        "pos": init(next(k), (cfg.n_patches + 1, e), s),
        "blocks": {
            "ln1_g": jnp.ones((L, e), dt),
            "ln1_b": jnp.zeros((L, e), dt),
            "wqkv": init(next(k), (L, e, 3, h, d), s),
            "bqkv": jnp.zeros((L, 3, h, d), dt),
            "wo": init(next(k), (L, h, d, e), s),
            "bo": jnp.zeros((L, e), dt),
            "ln2_g": jnp.ones((L, e), dt),
            "ln2_b": jnp.zeros((L, e), dt),
            "wi": init(next(k), (L, e, cfg.mlp_dim), s),
            "bi": jnp.zeros((L, cfg.mlp_dim), dt),
            "wo2": init(next(k), (L, cfg.mlp_dim, e), s),
            "bo2": jnp.zeros((L, e), dt),
        },
        "lnf_g": jnp.ones((e,), dt),
        "lnf_b": jnp.zeros((e,), dt),
        "head_w": init(next(k), (e, cfg.num_classes), (1.0 / e) ** 0.5),
        "head_b": jnp.zeros((cfg.num_classes,), dt),
    }


def vit_param_axes():
    return {
        "patch_w": P(None, "embed"),
        "patch_b": P("norm"),
        "cls": P(None, None, "norm"),
        "pos": P(None, "embed"),
        "blocks": {
            "ln1_g": P(None, "norm"),
            "ln1_b": P(None, "norm"),
            "wqkv": P(None, "embed", None, "heads", "kv"),
            "bqkv": P(None, None, "heads", "kv"),
            "wo": P(None, "heads", "kv", "embed"),
            "bo": P(None, "norm"),
            "ln2_g": P(None, "norm"),
            "ln2_b": P(None, "norm"),
            "wi": P(None, "embed", "mlp"),
            "bi": P(None, "mlp"),
            "wo2": P(None, "mlp", "embed"),
            "bo2": P(None, "norm"),
        },
        "lnf_g": P("norm"),
        "lnf_b": P("norm"),
        "head_w": P("embed", None),
        "head_b": P(None),
    }


def _layernorm(x, g, b, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def _encoder_block(x, layer, cfg: ViTConfig, mesh):
    from ..parallel.sharding import with_logical_constraint as wlc

    y = _layernorm(x, layer["ln1_g"], layer["ln1_b"])
    qkv = jnp.einsum("bse,ethd->bsthd", y, layer["wqkv"]) + layer["bqkv"]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if cfg.attention == "flash":
        from ..ops.attention import flash_attention

        o = flash_attention(q, k, v, causal=False)
    else:
        from ..ops.attention import reference_attention

        o = reference_attention(q, k, v, causal=False)
    x = x + (jnp.einsum("bshd,hde->bse", o, layer["wo"]) + layer["bo"]).astype(x.dtype)
    y = _layernorm(x, layer["ln2_g"], layer["ln2_b"])
    hdn = jax.nn.gelu(jnp.einsum("bse,ef->bsf", y, layer["wi"]) + layer["bi"])
    hdn = wlc(hdn, P("batch", "seq", "mlp"), mesh)
    x = x + (jnp.einsum("bsf,fe->bse", hdn, layer["wo2"]) + layer["bo2"]).astype(x.dtype)
    return wlc(x, P("batch", "seq", "act_embed"), mesh)


def patchify(images, cfg: ViTConfig):
    """[B, H, W, 3] → [B, n_patches, patch_dim] by pure reshape/transpose."""
    b, hh, ww, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, hh // p, p, ww // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (hh // p) * (ww // p), p * p * c)


def vit_apply(params, images, cfg: ViTConfig, mesh=None):
    """images: [B, H, W, 3] → logits [B, num_classes]."""
    from ..parallel.sharding import with_logical_constraint as wlc

    dt = jnp.dtype(cfg.dtype)
    x = patchify(images.astype(dt), cfg) @ params["patch_w"] + params["patch_b"]
    cls = jnp.broadcast_to(params["cls"], (x.shape[0], 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"][None]
    x = wlc(x, P("batch", "seq", "act_embed"), mesh)

    block = functools.partial(_encoder_block, cfg=cfg, mesh=mesh)

    def scan_body(x, layer):
        return block(x, layer), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    x = _layernorm(x[:, 0], params["lnf_g"], params["lnf_b"])
    logits = x.astype(jnp.float32) @ params["head_w"].astype(jnp.float32) + \
        params["head_b"].astype(jnp.float32)
    return wlc(logits, P("batch", None), mesh)


def vit_loss(params, images, labels, cfg: ViTConfig, mesh=None):
    logits = vit_apply(params, images, cfg, mesh)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
