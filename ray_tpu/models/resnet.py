"""ResNet family (ResNet-18/50) — BASELINE.md north-star config #2:
data-parallel ResNet-50 with allreduce over ICI.

The reference delegates vision models to torchvision inside Train release
tests; here the model is native JAX, TPU-first:

  - NHWC layout (TPU convolutions tile the channel axis onto the MXU lanes);
  - bf16 params/activations, f32 batch-norm statistics;
  - batch norm is functional: ``resnet_apply`` returns ``(logits, new_state)``
    in training mode, and running stats are a separate pytree so the
    data-parallel trainer can ``psum``-average them;
  - residual blocks over ``lax.scan`` where the stage geometry repeats
    (uniform blocks within a stage share a stacked param tree).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    # (blocks, channels) per stage; bottleneck expands channels ×4
    stages: Tuple[Tuple[int, int], ...] = ((3, 64), (4, 128), (6, 256), (3, 512))
    bottleneck: bool = True
    num_classes: int = 1000
    dtype: str = "bfloat16"

    @classmethod
    def resnet50(cls, **kw) -> "ResNetConfig":
        return cls(**kw)

    @classmethod
    def resnet18(cls, **kw) -> "ResNetConfig":
        kw.setdefault("stages", ((2, 64), (2, 128), (2, 256), (2, 512)))
        kw.setdefault("bottleneck", False)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "ResNetConfig":
        kw.setdefault("stages", ((1, 8), (1, 16)))
        kw.setdefault("bottleneck", False)
        kw.setdefault("num_classes", 10)
        return cls(**kw)

    @property
    def expansion(self) -> int:
        return 4 if self.bottleneck else 1


def _conv_init(key, kh, kw_, cin, cout, dt):
    fan_in = kh * kw_ * cin
    w = jax.random.normal(key, (kh, kw_, cin, cout)) * (2.0 / fan_in) ** 0.5
    return w.astype(dt)


def _bn_init(c, dt):
    return {"g": jnp.ones((c,), dt), "b": jnp.zeros((c,), dt)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def resnet_init(key, cfg: ResNetConfig):
    """-> (params, state).  state carries batch-norm running stats."""
    dt = jnp.dtype(cfg.dtype)
    keys = iter(jax.random.split(key, 256))
    params = {"stem": {"w": _conv_init(next(keys), 7, 7, 3, 64, dt),
                       "bn": _bn_init(64, dt)}}
    state = {"stem": _bn_state(64)}
    cin = 64
    for si, (n_blocks, ch) in enumerate(cfg.stages):
        cout = ch * cfg.expansion
        blocks, bstate = [], []
        for bi in range(n_blocks):
            stride = 2 if (bi == 0 and si > 0) else 1
            blk, bst = {}, {}
            if cfg.bottleneck:
                blk["conv1"] = {"w": _conv_init(next(keys), 1, 1, cin, ch, dt),
                                "bn": _bn_init(ch, dt)}
                blk["conv2"] = {"w": _conv_init(next(keys), 3, 3, ch, ch, dt),
                                "bn": _bn_init(ch, dt)}
                blk["conv3"] = {"w": _conv_init(next(keys), 1, 1, ch, cout, dt),
                                "bn": _bn_init(cout, dt)}
                bst = {"conv1": _bn_state(ch), "conv2": _bn_state(ch),
                       "conv3": _bn_state(cout)}
            else:
                blk["conv1"] = {"w": _conv_init(next(keys), 3, 3, cin, ch, dt),
                                "bn": _bn_init(ch, dt)}
                blk["conv2"] = {"w": _conv_init(next(keys), 3, 3, ch, cout, dt),
                                "bn": _bn_init(cout, dt)}
                bst = {"conv1": _bn_state(ch), "conv2": _bn_state(cout)}
            if stride != 1 or cin != cout:
                blk["proj"] = {"w": _conv_init(next(keys), 1, 1, cin, cout, dt),
                               "bn": _bn_init(cout, dt)}
                bst["proj"] = _bn_state(cout)
            blocks.append(blk)
            bstate.append(bst)
            cin = cout
        params[f"stage{si}"] = blocks
        state[f"stage{si}"] = bstate
    params["head"] = {
        "w": (jax.random.normal(next(keys), (cin, cfg.num_classes)) *
              (1.0 / cin) ** 0.5).astype(dt),
        "b": jnp.zeros((cfg.num_classes,), dt),
    }
    return params, state


def resnet_param_axes(params):
    """Logical axes: conv filters replicate; the classifier head and wide
    1x1 convs shard their output-channel axis over fsdp (ZeRO-3)."""

    def axes(path, x):
        if x.ndim == 4:
            return P(None, None, None, "embed")
        if x.ndim == 2:
            return P(None, "embed")
        return P(*([None] * x.ndim))

    return jax.tree_util.tree_map_with_path(axes, params)


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batchnorm(x, bn, st, train: bool, momentum=0.9, eps=1e-5):
    """Returns (y, new_state).  Stats in f32 regardless of activation dtype."""
    if train:
        x32 = x.astype(jnp.float32)
        mean = x32.mean((0, 1, 2))
        var = x32.var((0, 1, 2))
        new_st = {"mean": momentum * st["mean"] + (1 - momentum) * mean,
                  "var": momentum * st["var"] + (1 - momentum) * var}
    else:
        mean, var = st["mean"], st["var"]
        new_st = st
    inv = jax.lax.rsqrt(var + eps)
    y = (x.astype(jnp.float32) - mean) * inv
    y = y * bn["g"].astype(jnp.float32) + bn["b"].astype(jnp.float32)
    return y.astype(x.dtype), new_st


def _block_apply(x, blk, bst, cfg: ResNetConfig, stride, train):
    out_state = {}
    shortcut = x
    if cfg.bottleneck:
        y = _conv(x, blk["conv1"]["w"], 1)
        y, out_state["conv1"] = _batchnorm(y, blk["conv1"]["bn"], bst["conv1"], train)
        y = jax.nn.relu(y)
        y = _conv(y, blk["conv2"]["w"], stride)
        y, out_state["conv2"] = _batchnorm(y, blk["conv2"]["bn"], bst["conv2"], train)
        y = jax.nn.relu(y)
        y = _conv(y, blk["conv3"]["w"], 1)
        y, out_state["conv3"] = _batchnorm(y, blk["conv3"]["bn"], bst["conv3"], train)
    else:
        y = _conv(x, blk["conv1"]["w"], stride)
        y, out_state["conv1"] = _batchnorm(y, blk["conv1"]["bn"], bst["conv1"], train)
        y = jax.nn.relu(y)
        y = _conv(y, blk["conv2"]["w"], 1)
        y, out_state["conv2"] = _batchnorm(y, blk["conv2"]["bn"], bst["conv2"], train)
    if "proj" in blk:
        shortcut = _conv(x, blk["proj"]["w"], stride)
        shortcut, out_state["proj"] = _batchnorm(
            shortcut, blk["proj"]["bn"], bst["proj"], train)
    return jax.nn.relu(y + shortcut), out_state


def resnet_apply(params, state, images, cfg: ResNetConfig, *, train=False,
                 mesh=None):
    """images: [B, H, W, 3] → (logits [B, classes], new_state)."""
    from ..parallel.sharding import with_logical_constraint as wlc

    x = images.astype(jnp.dtype(cfg.dtype))
    x = wlc(x, P("batch", None, None, None), mesh)
    new_state = {}
    x = _conv(x, params["stem"]["w"], 2)
    x, new_state["stem"] = _batchnorm(x, params["stem"]["bn"], state["stem"], train)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for si, (n_blocks, _ch) in enumerate(cfg.stages):
        stage_state = []
        for bi in range(n_blocks):
            stride = 2 if (bi == 0 and si > 0) else 1
            x, bst = _block_apply(
                x, params[f"stage{si}"][bi], state[f"stage{si}"][bi],
                cfg, stride, train)
            stage_state.append(bst)
        new_state[f"stage{si}"] = stage_state
        x = wlc(x, P("batch", None, None, None), mesh)
    x = x.astype(jnp.float32).mean((1, 2))  # global average pool
    logits = x @ params["head"]["w"].astype(jnp.float32) + \
        params["head"]["b"].astype(jnp.float32)
    return wlc(logits, P("batch", None), mesh), new_state


def resnet_loss(params, state, images, labels, cfg: ResNetConfig, *,
                mesh=None):
    """Softmax cross-entropy; returns (loss, new_state)."""
    logits, new_state = resnet_apply(
        params, state, images, cfg, train=True, mesh=mesh)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    return nll, new_state
