"""Device-mesh construction for DP/FSDP/TP/SP/PP.

TPU-first design: the mesh is the unit of parallelism (not process groups).
Axes follow the standard recipe (scaling-book / maxtext conventions):

  - ``data``:  pure data parallelism (gradient psum over DCN or ICI)
  - ``fsdp``:  parameter/optimizer sharding (ZeRO-3 style all-gather)
  - ``model``: tensor parallelism (matmul-sharded, psum on contraction)
  - ``seq``:   sequence/context parallelism (ring attention / Ulysses)
  - ``stage``: pipeline parallelism across slices
  - ``expert``: expert parallelism (MoE dispatch via all_to_all)

``mesh_utils.create_device_mesh`` lays axes onto the physical ICI topology so
the innermost (most chatty) axes ride the fastest links.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np

AXES = ("data", "fsdp", "stage", "expert", "seq", "model")


@dataclass
class MeshConfig:
    data: int = 1
    fsdp: int = 1
    stage: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.fsdp, self.stage, self.expert, self.seq,
                self.model)

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.shape))

    @classmethod
    def for_devices(cls, n: int, *, model: int = 1, seq: int = 1, stage: int = 1,
                    expert: int = 1, fsdp: Optional[int] = None) -> "MeshConfig":
        """Fill the data/fsdp axes with whatever ``n`` leaves after the
        explicitly requested axes."""
        fixed = model * seq * stage * expert
        rest = n // fixed
        if rest * fixed != n:
            raise ValueError(
                f"{n} devices not divisible by model×seq×stage×expert = "
                f"{fixed}"
            )
        if fsdp is None:
            fsdp = rest
            data = 1
        else:
            data = rest // fsdp
            if data * fsdp != rest:
                raise ValueError(f"fsdp={fsdp} does not divide {rest}")
        return cls(data=data, fsdp=fsdp, stage=stage, expert=expert, seq=seq,
                   model=model)


def build_mesh(config: MeshConfig, devices: Optional[Sequence] = None):
    """Build a jax Mesh with all five axes (size-1 axes are free)."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) != config.num_devices:
        raise ValueError(
            f"mesh {config.shape} needs {config.num_devices} devices, "
            f"have {len(devices)}"
        )
    try:
        arr = mesh_utils.create_device_mesh(config.shape, devices=devices)
    except Exception:
        arr = np.asarray(devices).reshape(config.shape)
    return Mesh(arr, AXES)


def local_mesh(**axis_sizes):
    """Convenience: mesh over all local devices, e.g.
    ``local_mesh(model=2)`` → data axis absorbs the rest."""
    import jax

    cfg = MeshConfig.for_devices(len(jax.devices()), **axis_sizes)
    return build_mesh(cfg)
