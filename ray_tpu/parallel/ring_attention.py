"""Ring attention: blockwise context parallelism over the ``seq`` mesh axis.

Closes the reference's sequence-parallelism gap (SURVEY.md §5: no SP/CP/ring
attention anywhere in the reference — long context was delegated to external
engines).  TPU-native design: Q/K/V are sequence-sharded over the ``seq``
axis; each device computes attention of its local Q block against the K/V
block it currently holds, accumulating with the flash online-softmax rule,
while K/V blocks rotate around the ring via ``jax.lax.ppermute`` — the
collective rides neighbor ICI links, and XLA overlaps the permute with the
block matmuls.  Memory per device is O(S/n · S/n) per step instead of O(S²).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF


def _block_attn(q, k, v, scale, causal, q_block_idx, kv_block_idx, s_local):
    """One blockwise step: unnormalized (m, l, pv) contributions.
    q/k/v: [B, S_local, H, D]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = q_block_idx * s_local + jax.lax.broadcasted_iota(
            jnp.int32, (s_local, s_local), 0
        )
        k_pos = kv_block_idx * s_local + jax.lax.broadcasted_iota(
            jnp.int32, (s_local, s_local), 1
        )
        s = jnp.where((k_pos <= q_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # [B,H,Q]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)  # unnormalized
    return m, l, pv


def ring_attention_local(q, k, v, *, axis_name: str = "seq",
                         causal: bool = True,
                         softmax_scale: Optional[float] = None):
    """The shard_map-inner ring attention.  Call inside a shard_map whose
    in_specs shard the sequence dim of q/k/v over ``axis_name``.

    q/k/v: [B, S_local, H, D] (this device's sequence shard).
    """
    from ..collective.types import compat_axis_size

    n = compat_axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    m_acc = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l_acc = jnp.zeros((b, h, s_local), jnp.float32)
    o_acc = jnp.zeros((b, s_local, h, d), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        kv_idx = (my_idx - i) % n  # block the ring has delivered to us
        m_b, l_b, pv_b = _block_attn(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), scale, causal, my_idx, kv_idx, s_local,
        )
        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)  # rescale old accumulators
        beta = jnp.exp(m_b - m_new)  # rescale this block
        l_new = l_acc * alpha + l_b * beta
        o_new = (
            o_acc * alpha.transpose(0, 2, 1)[..., None]
            + pv_b * beta.transpose(0, 2, 1)[..., None]
        )
        # Rotate K/V to the next neighbor (single-hop ICI transfer).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, o_new, k_nxt, v_nxt

    m_acc, l_acc, o_acc, _, _ = jax.lax.fori_loop(
        0, n, step, (m_acc, l_acc, o_acc, k, v)
    )
    # Fully-masked rows can have l == 0 only if causal masking removed every
    # key, which cannot happen (the diagonal block always contains k<=q).
    out = o_acc / jnp.maximum(l_acc, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, *, causal: bool = True,
                   seq_axis: str = "seq", batch_axes=("data", "fsdp"),
                   head_axis: str = "model"):
    """Jit-compatible wrapper: shard_maps the ring over the mesh.
    q/k/v: [B, S, H, D] global arrays (S sharded over ``seq_axis``)."""
    from jax.sharding import PartitionSpec as P

    from ..collective.types import compat_shard_map

    spec = P(batch_axes, seq_axis, head_axis, None)
    inner = functools.partial(
        ring_attention_local, axis_name=seq_axis, causal=causal
    )
    return compat_shard_map(
        inner, mesh, (spec, spec, spec), spec
    )(q, k, v)
