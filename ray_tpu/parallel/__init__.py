from .mesh import MeshConfig, build_mesh, local_mesh  # noqa: F401
from .multislice import (  # noqa: F401
    MULTISLICE_RULES,
    MultiSliceConfig,
    build_multislice_mesh,
    default_rules_for_mesh,
    group_devices_by_slice,
)
from .pipeline import pipeline_local, pipelined  # noqa: F401
from .ring_attention import ring_attention, ring_attention_local  # noqa: F401
from .sharding import (  # noqa: F401
    DEFAULT_RULES,
    LogicalRules,
    logical_sharding,
    logical_spec,
    shard_pytree,
    sharding_tree,
    with_logical_constraint,
)
from .ulysses import ulysses_attention, ulysses_attention_local  # noqa: F401
