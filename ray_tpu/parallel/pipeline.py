"""Pipeline parallelism over the ``stage`` mesh axis (GPipe schedule).

Where the reference expresses pipelines as compiled actor DAGs with NCCL
channels (ray ``python/ray/dag/``, SURVEY.md §2.3), the TPU-native pipeline
is a single SPMD program: stage parameters are sharded over the ``stage``
axis, microbatch activations flow stage-to-stage via ``jax.lax.ppermute``
(neighbor ICI hops), and the whole schedule is one ``lax.fori_loop`` under
jit — XLA overlaps the permute of tick t with the compute of tick t+1.

Usage: a stack of structurally identical stage functions (e.g. transformer
layer groups); parameters carry a leading stage dimension.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp


def pipeline_local(stage_fn: Callable, stage_params, microbatches, *,
                   axis_name: str = "stage"):
    """shard_map-inner GPipe loop.

    stage_fn: (params_for_one_stage, x) -> y with x.shape == y.shape
    stage_params: this device's stage params (leading stage dim squeezed
        by the caller's in_specs, i.e. a [1, ...] tree — squeezed here)
    microbatches: [M, mb, ...] — full input, replicated across stages.
    Returns [M, mb, ...] outputs of the final stage (replicated).
    """
    from ..collective.types import compat_axis_size

    n = compat_axis_size(axis_name)
    my_stage = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)
    m = microbatches.shape[0]
    ticks = m + n - 1
    perm_fwd = [(i, (i + 1) % n) for i in range(n)]

    carry0 = jnp.zeros_like(microbatches[0])  # inter-stage activation buffer
    out0 = jnp.zeros_like(microbatches)

    def tick(t, state):
        carry, outs = state
        mb_idx = t - my_stage  # which microbatch this stage works on
        active = (mb_idx >= 0) & (mb_idx < m)
        # Stage 0 reads fresh input; others read what the ring delivered.
        x_in = jnp.where(
            my_stage == 0,
            jax.lax.dynamic_index_in_dim(
                microbatches, jnp.clip(mb_idx, 0, m - 1), keepdims=False
            ),
            carry,
        )
        y = stage_fn(params, x_in)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # Last stage records its finished microbatch.
        is_last = my_stage == n - 1
        outs = jax.lax.cond(
            active & is_last,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(mb_idx, 0, m - 1), axis=0
            ),
            lambda o: o,
            outs,
        )
        # Ship activations to the next stage (single ICI hop).
        carry = jax.lax.ppermute(y, axis_name, perm_fwd)
        return carry, outs

    _, outs = jax.lax.fori_loop(0, ticks, tick, (carry0, out0))
    # Only the last stage holds real outputs; replicate via psum (all other
    # stages contribute zeros).
    outs = jnp.where(my_stage == n - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)


def pipelined(stage_fn: Callable, mesh, *, axis_name: str = "stage",
              batch_axes=("data", "fsdp")):
    """Build a jit-compatible pipelined apply:
        fn(stacked_params, microbatches) -> outputs
    stacked_params: leading dim = num stages (sharded over ``axis_name``);
    microbatches: [M, mb, ...] with the mb batch dim sharded over
    ``batch_axes``."""
    from jax.sharding import PartitionSpec as P

    from ..collective.types import compat_shard_map

    inner = functools.partial(pipeline_local, stage_fn, axis_name=axis_name)

    def apply(stacked_params, microbatches):
        params_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
        x_spec = P(None, batch_axes)
        return compat_shard_map(
            inner, mesh, (params_specs, x_spec), x_spec
        )(stacked_params, microbatches)

    return apply
