"""Logical-axis sharding rules.

Models annotate arrays with *logical* axis names ("batch", "embed", "heads",
…); a rule table maps logical names to mesh axes.  Changing the parallelism
strategy = changing the table, not the model (the maxtext/flax
logical-axis-rules pattern, re-implemented standalone).

Logical axes are written as ``PartitionSpec`` of logical names (a
PartitionSpec is a pytree *leaf*, so trees of annotations map cleanly over
parameter trees):

    axes = {"wq": P("embed", "heads"), "bias": P(None)}
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple, Union

LogicalRules = Dict[str, Union[str, Tuple[str, ...], None]]

# The default recipe: batch splits over (data, fsdp); params shard their
# feature axes over fsdp (ZeRO-3) and their model-parallel axes over model;
# sequence splits over seq for context parallelism.
DEFAULT_RULES: LogicalRules = {
    "batch": ("data", "fsdp"),
    "seq": "seq",
    "embed": "fsdp",  # parameter axis (ZeRO-3 shard)
    "act_embed": None,  # activation feature axis (replicated across fsdp)
    "mlp": "model",
    "heads": "model",
    "kv": None,
    "vocab": "model",
    "stage": "stage",
    "norm": None,
    # MoE: the expert axis of per-expert params and of dispatched token
    # buffers shards over the expert mesh axis; XLA lowers the
    # dispatch/combine einsums to all_to_all over ICI.
    "expert": "expert",
    "capacity": None,
    "expert_mlp": "model",
}


def logical_spec(logical_axes, rules: Optional[LogicalRules] = None):
    """Map a PartitionSpec (or tuple) of logical names to a mesh-axis
    PartitionSpec."""
    from jax.sharding import PartitionSpec as P

    rules = rules if rules is not None else DEFAULT_RULES
    entries = []
    for name in tuple(logical_axes):
        if name is None:
            entries.append(None)
        else:
            entries.append(rules.get(name))
    return P(*entries)


def logical_sharding(mesh, logical_axes, rules: Optional[LogicalRules] = None):
    from jax.sharding import NamedSharding

    if rules is None and "dcn" in mesh.axis_names:
        # Multi-slice mesh: batch additionally spans the cross-slice dcn
        # axis (see parallel.multislice) — models need no changes.
        from .multislice import MULTISLICE_RULES

        rules = MULTISLICE_RULES
    return NamedSharding(mesh, logical_spec(logical_axes, rules))


def with_logical_constraint(x, logical_axes, mesh=None,
                            rules: Optional[LogicalRules] = None):
    """Inside jit: constrain intermediate activations to a logical sharding.
    No-op when no mesh is provided (single-device runs)."""
    import jax

    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules)
    )


def shard_pytree(params, axes_tree, mesh, rules: Optional[LogicalRules] = None):
    """Device-put a pytree of arrays according to a matching pytree of
    logical PartitionSpecs (PartitionSpec is a leaf, so the trees align)."""
    import jax

    def place(x, axes):
        if axes is None:
            axes = (None,) * x.ndim
        return jax.device_put(x, logical_sharding(mesh, axes, rules))

    return jax.tree.map(place, params, axes_tree)


def sharding_tree(axes_tree, mesh, rules: Optional[LogicalRules] = None):
    """Turn a tree of logical PartitionSpecs into NamedShardings (for use as
    jit in_shardings/out_shardings)."""
    import jax

    return jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules), axes_tree
    )
