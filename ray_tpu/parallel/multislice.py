"""Multi-slice meshes: ICI within a slice, DCN across slices.

SURVEY §2.3's cross-slice story: a TPU pod slice is one ICI domain; jobs
spanning slices communicate over DCN, which is an order of magnitude
slower — so the mesh must put the *least chatty* axis (pure data
parallelism: one gradient psum per step) across slices and keep
model/fsdp/seq traffic inside each slice.  This module builds such a mesh
as an outer ``dcn`` axis over per-slice sub-meshes and extends the logical
sharding rules so ``batch`` spans (dcn, data, fsdp) — XLA then inserts a
hierarchical gradient reduction (intra-slice reduce-scatter over ICI +
cross-slice all-reduce over DCN) on its own.

Reference has no multi-slice support to mirror (its GPU analog is
NCCL-over-IB across nodes); the design follows the jax multi-slice recipe
(``mesh_utils.create_hybrid_device_mesh``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

from .mesh import AXES, MeshConfig
from .sharding import DEFAULT_RULES, LogicalRules

MULTISLICE_AXES = ("dcn",) + AXES

# Logical rules for a dcn-extended mesh: cross-slice traffic is pure data
# parallelism; every other axis stays intra-slice.
MULTISLICE_RULES: LogicalRules = dict(
    DEFAULT_RULES, batch=("dcn", "data", "fsdp")
)


@dataclass
class MultiSliceConfig:
    num_slices: int
    per_slice: MeshConfig

    @property
    def shape(self):
        return (self.num_slices,) + self.per_slice.shape

    @property
    def num_devices(self) -> int:
        return int(self.num_slices * self.per_slice.num_devices)


def group_devices_by_slice(devices: Sequence, num_slices: int):
    """Partition devices into slices: real TPU devices carry
    ``slice_index``; virtual/CPU devices split into equal contiguous
    chunks (each chunk *modeling* one ICI domain)."""
    by_idx: Dict[int, list] = {}
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        for d in devices:
            by_idx.setdefault(d.slice_index, []).append(d)
        if len(by_idx) == num_slices:
            return [by_idx[i] for i in sorted(by_idx)]
    n = len(devices)
    per = n // num_slices
    if per * num_slices != n:
        raise ValueError(
            f"{n} devices not divisible into {num_slices} slices"
        )
    return [list(devices[i * per : (i + 1) * per]) for i in range(num_slices)]


def build_multislice_mesh(config: MultiSliceConfig,
                          devices: Optional[Sequence] = None):
    """Mesh with axes ('dcn', 'data', 'fsdp', 'stage', 'expert', 'seq',
    'model'): the outer axis crosses slices, inner axes stay inside one.

    On real multi-slice hardware uses ``create_hybrid_device_mesh`` (which
    knows DCN vs ICI link speeds); virtual devices fall back to a
    per-slice layout of contiguous chunks.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) != config.num_devices:
        raise ValueError(
            f"multislice mesh {config.shape} needs {config.num_devices} "
            f"devices, have {len(devices)}"
        )
    try:
        arr = mesh_utils.create_hybrid_device_mesh(
            config.per_slice.shape,
            dcn_mesh_shape=(config.num_slices,) + (1,) * len(AXES),
            devices=devices,
        )
        # hybrid mesh returns shape per_slice*dcn broadcast; normalize to
        # (num_slices, *per_slice.shape)
        arr = np.asarray(arr).reshape(config.shape)
    except Exception:
        slices = group_devices_by_slice(devices, config.num_slices)
        arr = np.stack(
            [
                np.asarray(s, dtype=object).reshape(config.per_slice.shape)
                for s in slices
            ]
        )
    return Mesh(arr, MULTISLICE_AXES)


def default_rules_for_mesh(mesh) -> LogicalRules:
    """Rule table matching the mesh's axes: dcn-extended meshes get the
    multislice batch mapping, plain meshes the default."""
    return MULTISLICE_RULES if "dcn" in mesh.axis_names else DEFAULT_RULES
