"""Scaling-efficiency harness: step-time curve over growing device meshes.

Evidences the north-star ICI scaling target (BASELINE.json: >=90% at
8->256 chips) on whatever devices are present.  On a CPU host it runs
against virtual XLA devices (``--xla_force_host_platform_device_count``),
where the measured retention reflects the collective/partitioning overhead
the compiler inserts — the quantity the sharding design controls — rather
than real ICI bandwidth; on a TPU slice the same harness measures the real
thing.  Also checks ring/Ulysses sequence-parallel attention against the
dense baseline for numerical parity (reference has no SP implementation to
compare against — SURVEY.md §5).

Run standalone (JSON lines on stdout):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m ray_tpu.parallel.scaling_bench

Or from bench.py, which re-emits the metrics in the driver's format.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence


def _build_step(cfg, mesh):
    import jax
    import optax

    from ray_tpu.models import gpt2_init, gpt2_loss, gpt2_param_axes
    from ray_tpu.parallel import shard_pytree

    params = gpt2_init(jax.random.PRNGKey(0), cfg)
    if mesh is not None:
        params = shard_pytree(params, gpt2_param_axes(), mesh)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: gpt2_loss(p, tokens, cfg, mesh)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1)), params, opt_state


def _time_step(step, params, opt_state, tokens, n_steps: int) -> float:
    """Mean seconds/step after compile+warmup, pipelined timing ending in a
    host sync (reliable on the remote-TPU tunnel backend).

    TWO warmup calls: the first compiles for the initial placements, and
    its RETURNED arrays can carry different shardings (donation + sharding
    propagation), so the second call may compile again — timing from the
    first loop iteration would silently include that recompile (this was
    the round-3 "partitioning overhead": a 1-device mesh appeared 5x
    slower than no mesh purely from the hidden recompile)."""
    p, o, loss = step(params, opt_state, tokens)
    _ = float(loss)
    p, o, loss = step(p, o, tokens)
    _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        p, o, loss = step(p, o, tokens)
    _ = float(loss)
    return (time.perf_counter() - t0) / n_steps


def _mesh_for(n: int, devices, seq_parallel: bool):
    from ray_tpu.parallel import MeshConfig, build_mesh

    if seq_parallel and n >= 2:
        seq = 2
        fsdp = n // 2
        cfg = MeshConfig(data=1, fsdp=fsdp, seq=seq, model=1)
    else:
        cfg = MeshConfig(data=1, fsdp=n, seq=1, model=1)
    return build_mesh(cfg, devices[:n])


def run_scaling_curve(
    device_counts: Sequence[int] = (1, 2, 4, 8),
    n_steps: int = 8,
    batch_per_device: int = 2,
    seq_len: int = 128,
) -> List[Dict]:
    """Weak-scaling partition retention across mesh sizes (FSDP axis).

    METHODOLOGY (one definition, emitted identically by bench.py and
    ``dryrun_multichip``): per-device batch is FIXED at
    ``batch_per_device`` (weak scaling).  For each mesh size n the same
    global batch (n * batch_per_device) also runs UNPARTITIONED on one
    device — identical total compute, zero partitioning — and

        retention(n) = t_unpartitioned(n) / t_partitioned(n)

    1.0 means the compiler-inserted sharding machinery (collectives,
    resharding, per-shard dispatch) is free; 0.9 means it costs 11%.
    This calibrated ratio is substrate-independent — on virtual CPU
    devices (all sharing one core) it isolates exactly the partitioning
    overhead, unpolluted by the fake "devices" contending for the core,
    which a naive per-device-throughput retention conflates.
    """
    import jax

    from ray_tpu.models import GPT2Config

    devices = jax.devices()
    counts = [n for n in device_counts if n <= len(devices)]
    cfg = GPT2Config(
        vocab_size=512, max_seq=seq_len, n_layer=4, n_head=8,
        d_model=256, dtype="float32", attention="dense",
    )
    out: List[Dict] = []
    for n in counts:
        batch = batch_per_device * n
        tokens = jax.numpy.zeros((batch, seq_len + 1), jax.numpy.int32)
        # Partitioned: n-device mesh.
        mesh = _mesh_for(n, devices, seq_parallel=False)
        step, params, opt_state = _build_step(cfg, mesh)
        dt = _time_step(step, params, opt_state, tokens, n_steps)
        # Reference: same global batch, one device, no partitioning.
        step_r, params_r, opt_r = _build_step(cfg, None)
        dt_ref = _time_step(step_r, params_r, opt_r, tokens, n_steps)
        retention = round(min(dt_ref / dt, 1.0), 4)
        # Feed the flight recorder's ICI scaling-efficiency gauge so the
        # measured retention is scrapeable from /metrics next to the
        # per-op collective telemetry (best-effort: the harness also runs
        # standalone, with no cluster to flush to).
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.record_scaling_efficiency(n, retention)
        except Exception:  # noqa: BLE001 — bench must not die on telemetry
            pass
        out.append(
            {
                "devices": n,
                "step_time_s": round(dt, 6),
                "step_time_unpartitioned_s": round(dt_ref, 6),
                "tokens_per_sec_per_device": round(
                    batch * seq_len / dt / n, 1
                ),
                "retention": retention,
            }
        )
    return out


def run_sp_parity(seq_len: int = 128) -> Dict:
    """Ring vs Ulysses vs dense: same loss on the same sharded inputs."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import GPT2Config, gpt2_init, gpt2_loss

    devices = jax.devices()
    if len(devices) < 2:
        return {"skipped": "needs >=2 devices"}
    n = 4 if len(devices) >= 4 else 2
    losses = {}
    tokens = None
    for attention in ("dense", "ring", "ulysses"):
        cfg = GPT2Config(
            vocab_size=512, max_seq=seq_len, n_layer=2, n_head=8,
            d_model=128, dtype="float32", attention=attention,
        )
        mesh = _mesh_for(n, devices, seq_parallel=(attention != "dense"))
        if tokens is None:
            key = jax.random.PRNGKey(7)
            tokens = jax.random.randint(
                key, (4, seq_len + 1), 0, cfg.vocab_size, jnp.int32
            )
        params = gpt2_init(jax.random.PRNGKey(0), cfg)
        loss = jax.jit(
            lambda p, t, c=cfg, m=mesh: gpt2_loss(p, t, c, m)
        )(params, tokens)
        losses[attention] = float(loss)
    dense = losses["dense"]
    return {
        "losses": {k: round(v, 6) for k, v in losses.items()},
        "ring_matches_dense": abs(losses["ring"] - dense) < 1e-3,
        "ulysses_matches_dense": abs(losses["ulysses"] - dense) < 1e-3,
    }


def main():
    import os

    # The box's sitecustomize force-selects the axon TPU backend; honor an
    # explicit JAX_PLATFORMS=cpu request (the virtual-device mesh path).
    plats = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in plats.lower() and "axon" not in plats.lower():
        import jax

        jax.config.update("jax_platforms", "cpu")
    curve = run_scaling_curve()
    for row in curve:
        print(json.dumps({"scaling": row}), flush=True)
    if len(curve) > 1:
        print(
            json.dumps(
                {
                    "scaling_summary": {
                        "max_devices": curve[-1]["devices"],
                        "retention_at_max": curve[-1]["retention"],
                    }
                }
            ),
            flush=True,
        )
    print(json.dumps({"sp_parity": run_sp_parity()}), flush=True)


if __name__ == "__main__":
    main()
