"""Ulysses-style sequence parallelism: all-to-all head↔sequence resharding.

The second half of closing the reference's SP gap (SURVEY.md §5).  Instead of
rotating K/V blocks (ring attention), Ulysses re-shards: inputs arrive
sequence-sharded [B, S/n, H, D]; one ``jax.lax.all_to_all`` over the ``seq``
axis turns them head-sharded [B, S, H/n, D]; each device runs *full-sequence*
attention for its head subset (any local kernel — including the Pallas flash
kernel); a second all-to-all restores sequence sharding.  Two all-to-alls of
activation size vs. ring's n single-hop permutes — better when n is small or
heads ≫ n; requires H divisible by the seq-axis size.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

from ..ops.attention import reference_attention


def ulysses_attention_local(
    q, k, v, *, axis_name: str = "seq", causal: bool = True,
    attn_fn: Optional[Callable] = None,
):
    """shard_map-inner Ulysses attention.  q/k/v: [B, S_local, H, D] with H
    divisible by the axis size."""
    from ..collective.types import compat_axis_size

    n = compat_axis_size(axis_name)
    h = q.shape[2]
    assert h % n == 0, f"heads ({h}) must divide by seq-axis size ({n})"
    attn = attn_fn or functools.partial(reference_attention, causal=causal)

    def seq_to_heads(x):
        # [B, S/n, H, D] → [B, S, H/n, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = attn(qh, kh, vh)
    return heads_to_seq(oh)


def ulysses_attention(q, k, v, mesh, *, causal: bool = True,
                      seq_axis: str = "seq", batch_axes=("data", "fsdp"),
                      attn_fn: Optional[Callable] = None):
    """Jit-compatible wrapper.  q/k/v: [B, S, H, D] global arrays (S sharded
    over ``seq_axis``; heads unsharded on that axis)."""
    from jax.sharding import PartitionSpec as P

    from ..collective.types import compat_shard_map

    spec = P(batch_axes, seq_axis, None, None)
    inner = functools.partial(
        ulysses_attention_local, axis_name=seq_axis, causal=causal,
        attn_fn=attn_fn,
    )
    return compat_shard_map(
        inner, mesh, (spec, spec, spec), spec
    )(q, k, v)
