"""Multi-agent environments + episode collection + independent learning.

Reference: ray ``rllib/env/multi_agent_env.py`` + ``multi_agent_env_runner.py``
+ ``rllib/core/rl_module/multi_rl_module.py``: an env steps a DICT of
per-agent actions and returns per-agent observations/rewards with a
``"__all__"`` done flag; the runner collects per-agent episodes; policies
map to agents through a ``policy_mapping_fn`` (agents may share one policy
or train independent ones).

This module provides the protocol, the episode collector, and
``IndependentTrainer``: per-policy REINFORCE-with-baseline learners over a
``MultiRLModule`` of discrete policy modules — the minimal multi-agent
learning stack the smoke envs need, structured so richer learners (PPO
losses per policy) slot in.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .rl_module import DiscretePolicyModule, MultiRLModule, RLModuleSpec

ALL_DONE = "__all__"


class MultiAgentEnv:
    """Protocol: subclass with ``agents``, ``observation_sizes``,
    ``action_sizes`` dicts and dict-valued reset/step."""

    agents: List[str]

    def reset(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        """-> (obs_dict, reward_dict, done_dict incl ALL_DONE, info)."""
        raise NotImplementedError


class TwoAgentCoopEnv(MultiAgentEnv):
    """Smoke env: two agents each see a target bit and earn +1 when BOTH
    match their action to their own target (cooperative coordination —
    learnable only if each agent's policy reads its own observation)."""

    agents = ["a0", "a1"]
    observation_sizes = {"a0": 2, "a1": 2}
    action_sizes = {"a0": 2, "a1": 2}

    def __init__(self, seed: int = 0, max_steps: int = 32):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self._t = 0
        self._targets: Dict[str, int] = {}

    def _obs(self) -> Dict[str, np.ndarray]:
        return {
            a: np.eye(2, dtype=np.float32)[self._targets[a]]
            for a in self.agents
        }

    def reset(self):
        self._t = 0
        self._targets = {
            a: int(self.rng.integers(0, 2)) for a in self.agents
        }
        return self._obs()

    def step(self, actions):
        both = all(
            int(actions[a]) == self._targets[a] for a in self.agents
        )
        rewards = {a: 1.0 if both else 0.0 for a in self.agents}
        self._t += 1
        done = self._t >= self.max_steps
        self._targets = {
            a: int(self.rng.integers(0, 2)) for a in self.agents
        }
        dones = {a: done for a in self.agents}
        dones[ALL_DONE] = done
        return self._obs(), rewards, dones, {}


class MultiAgentEpisode:
    """Per-agent transition columns for one episode (reference
    ``MultiAgentEpisode``)."""

    def __init__(self, agents):
        self.steps: Dict[str, Dict[str, list]] = {
            a: {"obs": [], "actions": [], "rewards": []} for a in agents
        }
        self.total_reward = 0.0

    def add(self, agent, obs, action, reward):
        s = self.steps[agent]
        s["obs"].append(np.asarray(obs, np.float32))
        s["actions"].append(int(action))
        s["rewards"].append(float(reward))
        self.total_reward += float(reward)


def collect_episodes(
    env: MultiAgentEnv,
    module: MultiRLModule,
    params: Dict[str, Any],
    policy_mapping_fn: Callable[[str], str],
    n_episodes: int,
    key,
) -> List[MultiAgentEpisode]:
    """Roll the env with per-agent policies (exploration forward)."""
    import jax

    episodes = []
    for _ in range(n_episodes):
        ep = MultiAgentEpisode(env.agents)
        obs = env.reset()
        done = False
        while not done:
            actions = {}
            for agent, o in obs.items():
                pid = policy_mapping_fn(agent)
                key, sub = jax.random.split(key)
                out = module[pid].forward_exploration(
                    params[pid], {"obs": o[None]}, sub
                )
                actions[agent] = int(np.asarray(out["actions"])[0])
            next_obs, rewards, dones, _ = env.step(actions)
            for agent in obs:
                ep.add(agent, obs[agent], actions[agent], rewards[agent])
            obs = next_obs
            done = bool(dones.get(ALL_DONE, False))
        episodes.append(ep)
    return episodes


class IndependentTrainer:
    """Independent per-policy learners over a MultiRLModule (the
    reference's independent-learning mode of multi-agent training)."""

    def __init__(
        self,
        env_maker: Callable[[], MultiAgentEnv],
        policy_mapping_fn: Optional[Callable[[str], str]] = None,
        hidden: int = 32,
        lr: float = 3e-2,
        gamma: float = 0.99,
        seed: int = 0,
    ):
        import jax
        import optax

        self.env_maker = env_maker
        probe = env_maker()
        self.policy_mapping_fn = policy_mapping_fn or (lambda agent: agent)
        policy_ids = sorted(
            {self.policy_mapping_fn(a) for a in probe.agents}
        )
        mods = {}
        for pid in policy_ids:
            agent = next(
                a for a in probe.agents if self.policy_mapping_fn(a) == pid
            )
            mods[pid] = RLModuleSpec(
                DiscretePolicyModule, {"hidden": hidden}
            ).build(
                probe.observation_sizes[agent], probe.action_sizes[agent]
            )
        self.module = MultiRLModule(mods)
        self.params = self.module.init_state(jax.random.PRNGKey(seed))
        self.gamma = gamma
        self._key = jax.random.PRNGKey(seed + 1)
        self.tx = optax.adam(lr)
        self.opt_state = {
            pid: self.tx.init(self.params[pid]) for pid in policy_ids
        }

        def make_update(mod):
            import jax.numpy as jnp

            def update(params, opt_state, obs, actions, returns):
                def loss(p):
                    out = mod.forward_train(p, {"obs": obs})
                    logp_all = jax.nn.log_softmax(out["logits"])
                    logp = jnp.take_along_axis(
                        logp_all, actions[:, None], axis=1
                    )[:, 0]
                    baseline = returns.mean()
                    adv = returns - baseline
                    return -(logp * adv).mean()

                lv, grads = jax.value_and_grad(loss)(params)
                updates, opt_state = self.tx.update(grads, opt_state, params)
                import optax as _optax

                return _optax.apply_updates(params, updates), opt_state, lv

            return jax.jit(update)

        self._updates = {
            pid: make_update(self.module[pid]) for pid in policy_ids
        }
        self._env = env_maker()

    def train(self, episodes_per_iter: int = 8) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        self._key, sub = jax.random.split(self._key)
        eps = collect_episodes(
            self._env, self.module, self.params, self.policy_mapping_fn,
            episodes_per_iter, sub,
        )
        # Batch per POLICY (agents sharing a policy pool their data).
        per_policy: Dict[str, Dict[str, list]] = {}
        for ep in eps:
            for agent, cols in ep.steps.items():
                pid = self.policy_mapping_fn(agent)
                acc = per_policy.setdefault(
                    pid, {"obs": [], "actions": [], "returns": []}
                )
                rets, g = [], 0.0
                for r in reversed(cols["rewards"]):
                    g = r + self.gamma * g
                    rets.append(g)
                acc["obs"].extend(cols["obs"])
                acc["actions"].extend(cols["actions"])
                acc["returns"].extend(reversed(rets))
        losses = {}
        for pid, acc in per_policy.items():
            self.params[pid], self.opt_state[pid], lv = self._updates[pid](
                self.params[pid],
                self.opt_state[pid],
                jnp.asarray(np.stack(acc["obs"])),
                jnp.asarray(np.asarray(acc["actions"], np.int32)),
                jnp.asarray(np.asarray(acc["returns"], np.float32)),
            )
            losses[pid] = float(lv)
        mean_r = float(np.mean([ep.total_reward for ep in eps]))
        return {"episode_reward_mean": mean_r, "policy_losses": losses}
