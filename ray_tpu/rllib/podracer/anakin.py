"""Anakin — TPU-resident vectorized envs + learner in ONE jitted loop.

Podracer (arxiv 2104.06272) §2: when the environment itself is written
in jax, the entire rollout+learn cycle compiles to a single XLA program
— ``lax.scan`` unrolls the env/policy interaction, a second scan chains
whole updates, and ``pmap`` replicates the loop across devices with
gradients ``pmean``-ed over the device axis.  Parameters, env states,
and trajectories NEVER leave the chip; Python only triggers the next
compiled chunk.  Against the host-loop IMPALA (Python env stepping, one
RPC round per rollout) this is the difference between thousands and
millions of env steps per second — ``bench.py rl`` measures the ratio
in one interleaved window.

The loss is IMPALA's V-trace (``rllib.impala.make_vtrace_loss``) vmapped
over the env axis; on-policy the importance ratios are exactly 1, so it
reduces to n-step actor-critic — but the SAME code path serves both, and
the same trained policy can later be served by Sebulba runners.

Chip sharing: an Anakin job binds only the devices in
``AnakinConfig.num_devices`` (default: all local), so several jobs — or
an Anakin job next to a serving workload — partition one host's chips.
``anakin_actor`` wraps a trainer in a remote actor pinned to a
``PodracerPlacement`` bundle so the placement-group scheduler arbitrates
that sharing cluster-wide.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from ..algorithm import Algorithm, AlgorithmConfig
from ..impala import make_vtrace_loss

import ray_tpu


class AnakinConfig(AlgorithmConfig):
    """Fluent config for the Anakin trainer.

    ``environment()`` takes a *jax env instance* (``CartPoleJax``-style
    functional ``reset``/``step`` with auto-reset), not a maker — the
    env is traced into the compiled loop, not instantiated per actor.
    """

    def __init__(self):
        super().__init__()
        self.jax_env: Optional[Any] = None
        self.num_envs_per_device = 64
        self.unroll_length = 16
        self.updates_per_step = 32  # scanned updates per training_step
        self.num_devices = 0  # 0 = every local device
        self.hidden = 32
        self.lr = 3e-3
        self.entropy_coeff = 0.01
        self.value_coeff = 0.5
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0

    def environment(self, env) -> "AnakinConfig":  # type: ignore[override]
        self.jax_env = env
        return self


class Anakin(Algorithm):
    """TPU-resident trainer: one ``pmap``-ped program per training_step."""

    def setup(self, config: AnakinConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ..env import CartPoleJax
        from ..ppo import _init_policy, _policy_forward

        env = config.jax_env if config.jax_env is not None else CartPoleJax()
        if not hasattr(env, "num_actions"):
            raise ValueError(
                "Anakin needs a discrete-action jax env (num_actions); "
                f"got {type(env).__name__}"
            )
        self.env = env
        self.devices = jax.local_devices()
        if config.num_devices:
            if config.num_devices > len(self.devices):
                raise ValueError(
                    f"num_devices={config.num_devices} > "
                    f"{len(self.devices)} local devices"
                )
            self.devices = self.devices[: config.num_devices]
        D = len(self.devices)
        E = config.num_envs_per_device
        T = config.unroll_length
        U = config.updates_per_step
        self._shape = (D, E, T, U)

        key = jax.random.PRNGKey(config.seed)
        params = _init_policy(
            key, env.observation_size, env.num_actions, config.hidden
        )
        self.tx = optax.adam(config.lr)
        opt_state = self.tx.init(params)
        tx = self.tx

        loss_fn = make_vtrace_loss(
            gamma=config.gamma,
            rho_bar=config.vtrace_clip_rho,
            c_bar=config.vtrace_clip_c,
            value_coeff=config.value_coeff,
            entropy_coeff=config.entropy_coeff,
        )

        def one_update(carry, _):
            """Rollout T steps across this device's E envs, then one
            v-trace update — all inside the compiled loop."""
            params, opt_state, env_state, obs, key = carry
            key, rollout_key = jax.random.split(key)

            def env_step(c, _):
                env_state, obs, k = c
                k, k_act, k_env = jax.random.split(k, 3)
                logits, values = _policy_forward(params, obs)
                actions = jax.random.categorical(k_act, logits)
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, actions[:, None], axis=1
                )[:, 0]
                env_keys = jax.random.split(k_env, E)
                env_state, nobs, rew, done = jax.vmap(env.step)(
                    env_keys, env_state, actions
                )
                out = {
                    "obs": obs,
                    "actions": actions,
                    "rewards": rew,
                    "dones": done.astype(jnp.float32),
                    "logp_old": logp,
                }
                return (env_state, nobs, k), out

            (env_state, obs, _), traj = jax.lax.scan(
                env_step, (env_state, obs, rollout_key), None, length=T
            )
            _, last_values = _policy_forward(params, obs)
            # traj leaves are time-major (T, E, ...); the shared loss is
            # per-trajectory time-major, so vmap it over the env axis.
            batch = {
                k: jnp.moveaxis(v, 0, 1) for k, v in traj.items()
            }
            batch["last_value"] = last_values

            def mean_loss(p):
                losses, _aux = jax.vmap(
                    lambda b: loss_fn(p, b), in_axes=(0,)
                )(batch)
                return jnp.mean(losses)

            loss, grads = jax.value_and_grad(mean_loss)(params)
            grads = jax.lax.pmean(grads, axis_name="devices")
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics = {
                "loss": loss,
                "reward_mean": jnp.mean(traj["rewards"]),
                "done_rate": jnp.mean(traj["dones"]),
            }
            return (params, opt_state, env_state, obs, key), metrics

        def learn_chunk(params, opt_state, env_state, obs, key):
            carry, metrics = jax.lax.scan(
                one_update, (params, opt_state, env_state, obs, key),
                None, length=U,
            )
            # Mean over the update chunk; the last update's loss is kept
            # separately as the freshest learning signal.
            summary = {
                "loss": metrics["loss"][-1],
                "loss_mean": jnp.mean(metrics["loss"]),
                "reward_mean": jnp.mean(metrics["reward_mean"]),
                "done_rate": jnp.mean(metrics["done_rate"]),
            }
            return carry, summary

        self._learn = jax.pmap(
            learn_chunk, axis_name="devices", devices=self.devices
        )

        # Greedy-policy evaluation, jitted on one device: mean FIRST-
        # episode return over eval_envs fresh envs.
        max_steps = int(getattr(env, "max_steps", 200))

        def eval_fn(params, key, num_envs):
            keys = jax.random.split(key, num_envs)
            state, obs = jax.vmap(env.reset)(keys)
            alive = jnp.ones(num_envs, jnp.float32)
            ret = jnp.zeros(num_envs, jnp.float32)

            def step(c, _):
                state, obs, alive, ret, k = c
                logits, _ = _policy_forward(params, obs)
                actions = jnp.argmax(logits, axis=-1)
                k, sub = jax.random.split(k)
                ekeys = jax.random.split(sub, num_envs)
                state, obs, rew, done = jax.vmap(env.step)(
                    ekeys, state, actions
                )
                ret = ret + rew * alive
                alive = alive * (1.0 - done.astype(jnp.float32))
                return (state, obs, alive, ret, k), None

            (_, _, _, ret, _), _ = jax.lax.scan(
                step, (state, obs, alive, ret, key), None, length=max_steps
            )
            return jnp.mean(ret)

        self._eval = jax.jit(eval_fn, static_argnums=(2,))

        # Device-resident replicated training state.
        self._params = jax.device_put_replicated(params, self.devices)
        self._opt_state = jax.device_put_replicated(opt_state, self.devices)
        reset_keys = jax.random.split(
            jax.random.PRNGKey(config.seed + 1), D * E
        ).reshape(D, E, 2)
        self._env_state, self._obs = jax.pmap(
            jax.vmap(env.reset), devices=self.devices
        )(reset_keys)
        self._keys = jax.random.split(
            jax.random.PRNGKey(config.seed + 2), D
        )
        self.total_env_steps = 0
        self.total_updates = 0

    # ------------------------------------------------------------ lifecycle
    def training_step(self) -> Dict[str, Any]:
        import jax

        from ray_tpu.util import flight_recorder

        D, E, T, U = self._shape
        t0 = time.perf_counter()
        carry, summary = self._learn(
            self._params, self._opt_state, self._env_state, self._obs,
            self._keys,
        )
        (self._params, self._opt_state, self._env_state, self._obs,
         self._keys) = carry
        summary = jax.tree.map(lambda x: float(np.asarray(x[0])), summary)
        dt = time.perf_counter() - t0
        env_steps = D * E * T * U
        self.total_env_steps += env_steps
        self.total_updates += U
        flight_recorder.record_rl_rollout("anakin", env_steps, dt, devices=D)
        flight_recorder.record_rl_update("anakin", n=U)
        done_rate = summary["done_rate"]
        return {
            "num_env_steps_sampled": env_steps,
            "env_steps_per_s": env_steps / max(dt, 1e-9),
            "num_learner_updates": U,
            "episode_len_mean": 1.0 / max(done_rate, 1e-6),
            "num_devices": D,
            "total_env_steps": self.total_env_steps,
            **summary,
        }

    def evaluate(self, num_envs: int = 16, seed: int = 0) -> float:
        """Mean greedy first-episode return of the current policy."""
        import jax

        params = jax.tree.map(lambda x: x[0], self._params)
        return float(
            self._eval(params, jax.random.PRNGKey(seed), num_envs)
        )

    def resize(self, num_devices: int) -> Dict[str, Any]:
        """Elastic world-size change: re-form the pmap gang over
        ``num_devices`` devices without losing learning progress.

        Single-replica params come off-device (``get_state``), the whole
        compiled loop is rebuilt for the new device set (``setup``), and
        the params re-replicate bit-identically (``set_state`` — the
        optimizer state re-initializes, the same policy as a
        restore-from-checkpoint crossover).  Step counters survive the
        rebuild; per-device batch shape is unchanged, so the GLOBAL batch
        scales with the device count — callers accounting for lr/batch
        coupling read ``num_devices`` out of the returned dict."""
        from ray_tpu.util import flight_recorder

        old = len(self.devices)
        if num_devices == old:
            return {"num_devices": old, "previous": old}
        state = self.get_state()
        steps, updates = self.total_env_steps, self.total_updates
        self.config.num_devices = num_devices
        self.setup(self.config)
        self.set_state(state)
        self.total_env_steps, self.total_updates = steps, updates
        flight_recorder.record_elastic_resize(
            "grow" if num_devices > old else "shrink"
        )
        return {"num_devices": len(self.devices), "previous": old}

    def get_state(self) -> Dict[str, Any]:
        import jax

        params = jax.tree.map(
            lambda x: np.asarray(x[0]), self._params
        )
        return {"params": params}

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax

        self._params = jax.device_put_replicated(
            state["params"], self.devices
        )
        self._opt_state = jax.device_put_replicated(
            self.tx.init(state["params"]), self.devices
        )


AnakinConfig.ALGO_CLS = Anakin


# ------------------------------------------------- placement composition
@ray_tpu.remote
class AnakinWorker:
    """An Anakin trainer wrapped in an actor so the placement-group
    scheduler decides which chips it may bind — the chip-sharing story:
    several Anakin jobs (or Anakin next to serving) each pin to a
    ``PodracerPlacement`` actor bundle and see only their share."""

    def __init__(self, config: AnakinConfig):
        self.algo = Anakin(config)

    def train(self) -> Dict[str, Any]:
        return self.algo.train()

    def evaluate(self, num_envs: int = 16, seed: int = 0) -> float:
        return self.algo.evaluate(num_envs, seed)

    def get_state(self) -> Dict[str, Any]:
        return self.algo.get_state()

    def set_state(self, state: Dict[str, Any]) -> None:
        self.algo.set_state(state)

    def resize(self, num_devices: int) -> Dict[str, Any]:
        return self.algo.resize(num_devices)

    def prepare_evict(self) -> bytes:
        """Checkpoint-then-evict hook: pickle the learner state so the
        runtime parks it in the cluster KV (namespace ``eviction``)
        before this trainer's bundle is reclaimed — a preempted Anakin
        job resumes from here bit-identical (docs/scheduling.md)."""
        import pickle

        return pickle.dumps(self.get_state())


def anakin_actor(config: AnakinConfig, scheduling_strategy=None,
                 **actor_options):
    """Spawn an ``AnakinWorker`` (optionally pinned to a placement-group
    bundle via ``scheduling_strategy=placement.actor_strategy(i)``)."""
    opts = dict(actor_options)
    if scheduling_strategy is not None:
        opts["scheduling_strategy"] = scheduling_strategy
    if opts:
        return AnakinWorker.options(**opts).remote(config)
    return AnakinWorker.remote(config)
