"""``bench.py rl`` backend — podracer throughput stages.

Run as a subprocess (``python -m ray_tpu.rllib.podracer.bench_rl
[--quick]``) so the 8-virtual-device XLA flags bind before jax imports;
each stage prints one ``{"rl": {...}}`` JSON line that ``bench.py``
re-emits into the summary.

Stages:

- ``rl_anakin_env_steps_per_s`` across 1→2→4→8 devices (one pmap
  compile per width, rate measured post-warmup) plus the 8-device
  scaling efficiency vs linear;
- ``rl_anakin_vs_host_loop`` — Anakin against the host-loop IMPALA
  (Python envs in runner actors, learner on the driver), both measured
  as end-to-end env-steps/s in ONE interleaved window (this box swings
  ~2x window-to-window, so A and B alternate within the same window and
  the ratio is trustworthy even when the absolute rates are not);
- ``rl_sebulba_learner_steps_per_s`` — Sebulba learner updates/s with
  env throughput and mean staleness alongside.

``--quick`` shrinks everything to a smoke (1 device, tiny unrolls) —
that's the path tier-1 pins via tests/test_rllib_podracer.py.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List


def _emit(row: Dict[str, Any]) -> Dict[str, Any]:
    print(json.dumps({"rl": row}), flush=True)
    return row


def _anakin_config(num_devices: int, quick: bool):
    from .anakin import AnakinConfig

    cfg = AnakinConfig()
    cfg.num_devices = num_devices
    cfg.num_envs_per_device = 16 if quick else 64
    cfg.unroll_length = 8 if quick else 16
    cfg.updates_per_step = 4 if quick else 20
    cfg.seed = 0
    return cfg


def _anakin_rate(algo, trials: int) -> float:
    """Mean post-warmup env-steps/s over ``trials`` training_steps."""
    rates = []
    for _ in range(trials):
        rates.append(algo.train()["env_steps_per_s"])
    return float(sum(rates) / len(rates))


def bench_anakin_scaling(quick: bool = False) -> List[Dict[str, Any]]:
    """Anakin env-step throughput at 1, 2, 4, 8 devices."""
    import jax

    widths = [1] if quick else [1, 2, 4, 8]
    widths = [w for w in widths if w <= len(jax.local_devices())]
    trials = 2 if quick else 3
    rows = []
    rates = {}
    for d in widths:
        algo = _anakin_config(d, quick).build()
        algo.train()  # warmup: pmap compile + first chunk
        rate = _anakin_rate(algo, trials)
        rates[d] = rate
        cfg = algo.config
        # Device count in the NAME: bench.py's one-line summary keys by
        # metric, and the scaling story needs every width to survive.
        rows.append(_emit({
            "metric": f"rl_anakin_env_steps_per_s_{d}dev",
            "value": round(rate, 1),
            "devices": d,
            "envs_per_device": cfg.num_envs_per_device,
            "unroll": cfg.unroll_length,
        }))
    if len(widths) > 1:
        top = widths[-1]
        rows.append(_emit({
            "metric": "rl_anakin_scaling_efficiency",
            "value": round(rates[top] / (top * rates[1]), 4),
            "devices": top,
        }))
    return rows


def bench_anakin_vs_host_loop(quick: bool = False) -> List[Dict[str, Any]]:
    """Anakin vs host-loop IMPALA, end-to-end env-steps/s, interleaved.

    Needs a running ray_tpu cluster (IMPALA's env runners are actors).
    Both sides include their learner update — this is trainer
    throughput, not bare env stepping.
    """
    from ray_tpu.rllib import IMPALAConfig

    anakin = _anakin_config(1, quick).build()
    anakin.train()  # warmup/compile outside the measured window

    icfg = (
        IMPALAConfig()
        .env_runners(2, rollout_steps=32 if quick else 128)
        .training(batches_per_step=2 if quick else 4)
    )
    impala = icfg.build()
    impala.train()  # warmup: runner spin-up + jit

    trials = 2 if quick else 3
    anakin_rates, impala_rates = [], []
    for _ in range(trials):
        # ONE window, A/B interleaved back-to-back.
        anakin_rates.append(anakin.train()["env_steps_per_s"])
        t0 = time.perf_counter()
        r = impala.train()
        impala_rates.append(
            r["num_env_steps_sampled"] / max(time.perf_counter() - t0, 1e-9)
        )
    impala.stop()
    a = sum(anakin_rates) / len(anakin_rates)
    b = sum(impala_rates) / len(impala_rates)
    return [_emit({
        "metric": "rl_anakin_vs_host_loop",
        "value": round(a, 1),
        "baseline": round(b, 1),
        "ratio": round(a / b, 3),
        "guard": ">1.0",
        "anakin_devices": 1,
        "impala_runners": 2,
        "trials": trials,
    })]


def bench_sebulba(quick: bool = False) -> List[Dict[str, Any]]:
    """Sebulba learner-update and env-step throughput (needs cluster)."""
    from .sebulba import SebulbaConfig

    cfg = SebulbaConfig()
    cfg.num_env_runners = 2
    cfg.envs_per_runner = 2 if quick else 4
    cfg.rollout_steps = 16 if quick else 64
    cfg.batches_per_step = 4 if quick else 8
    cfg.seed = 0
    algo = cfg.build()
    algo.train()  # warmup: actor spin-up + jit compile
    trials = 1 if quick else 3
    lps, eps, stale = [], [], []
    for _ in range(trials):
        r = algo.train()
        lps.append(r["learner_steps_per_s"])
        eps.append(r["num_env_steps_sampled"])
        stale.append(r["staleness_mean"])
    algo.stop()
    return [_emit({
        "metric": "rl_sebulba_learner_steps_per_s",
        "value": round(sum(lps) / len(lps), 2),
        "runners": cfg.num_env_runners,
        "envs_per_runner": cfg.envs_per_runner,
        "staleness_mean": round(sum(stale) / len(stale), 2),
    })]


def main(argv=None) -> int:
    import sys

    quick = "--quick" in (argv if argv is not None else sys.argv[1:])
    bench_anakin_scaling(quick)

    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        bench_anakin_vs_host_loop(quick)
        bench_sebulba(quick)
    finally:
        ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
