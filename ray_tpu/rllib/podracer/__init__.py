"""``ray_tpu.rllib.podracer`` — Podracer architectures for scalable RL.

Reference: "Podracer architectures for scalable Reinforcement Learning"
(arxiv 2104.06272).  Two trainers on top of the task/actor/placement-
group/collective runtime:

- **Anakin** (``anakin.py``): envs AND learner fused into one jitted
  TPU-resident loop — pure-jax vectorized envs stepped under
  ``lax.scan``, ``pmap`` over devices, parameters never leave the chip.
  Use when the env is (re)writable in jax: env throughput scales with
  chips, not Python.
- **Sebulba** (``sebulba.py``): host-side env-runner actors (arbitrary
  Python envs) doing batched inference on their local "actor" devices,
  trajectories queued to the "learner" devices with bounded-staleness
  V-trace correction (IMPALA's loss) and parameter broadcast over the
  zero-copy ``StageChannel`` path.  Use when the env cannot be jitted.

``docs/rllib.md`` has the decision table, placement shapes, and knobs.
"""

from .anakin import Anakin, AnakinConfig  # noqa: F401
from .sebulba import (  # noqa: F401
    Sebulba,
    SebulbaConfig,
    SebulbaEnvRunner,
    evaluate_policy_numpy,
)
