"""Sebulba — host-side envs feeding split actor/learner device pipelines.

Podracer (arxiv 2104.06272) §3: when the environment can't be jitted
(simulators, games, anything Python), keep envs on HOST actors but make
every policy decision a *batched* device computation: each env-runner
actor steps a batch of envs and runs one batched forward per timestep on
its local "actor" device; finished unrolls stream to the learner, which
applies IMPALA's V-trace loss (``rllib.impala.make_vtrace_loss`` vmapped
over the trajectory batch) on the "learner" devices and broadcasts fresh
parameters back over the ``collective.p2p.StageChannel`` zero-copy path
— serialized once, fanned out to every runner, adopted at the next
unroll boundary.

Staleness is bounded, not hidden: every trajectory carries the parameter
version that produced it; the learner corrects up to
``max_staleness`` versions with the V-trace rho/c clipping and DROPS
anything older (counted, surfaced in the result dict).  Runner death is
harvested by the ``FaultTolerantActorManager`` — killed, respawned with
current params into the same slot (bounded restarts), resubmitted — the
learner's wait never stalls on a corpse.

Placement: ``SebulbaConfig.use_placement`` reserves device-role bundles
(``core.placement.podracer_placement_group``) — runner actors pin to
"actor" bundles, keeping the learner's chips and the inference chips
disjoint, and letting several RL jobs (or RL next to serving) share one
cluster under the normal placement-group arbitration.
"""

from __future__ import annotations

import logging
import os
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.core.serialization import dumps_function

from ..algorithm import Algorithm, AlgorithmConfig
from ..actor_manager import FaultTolerantActorManager
from ..impala import make_vtrace_loss

logger = logging.getLogger(__name__)


def evaluate_policy_numpy(params, env_maker, episodes: int = 6,
                          seed: int = 0, greedy: bool = True) -> float:
    """Mean episode return of ``params`` over fresh env copies (host
    rollout, no cluster) — the seeded eval both learning tests and the
    bench use."""
    from ..ppo import _np_policy_forward

    returns: List[float] = []
    rng = np.random.default_rng(seed)
    for ep in range(episodes):
        env = env_maker()
        env.rng = np.random.default_rng(seed * 997 + ep)
        obs = env.reset()
        done, total = False, 0.0
        while not done:
            logits, _ = _np_policy_forward(params, obs)
            if greedy:
                action = int(np.argmax(logits))
            else:
                z = logits - logits.max()
                probs = np.exp(z) / np.exp(z).sum()
                action = int(rng.choice(len(probs), p=probs))
            obs, r, done, _ = env.step(action)
            total += r
        returns.append(total)
    return float(np.mean(returns))


@ray_tpu.remote
class SebulbaEnvRunner:
    """Host-side sampling actor stepping a BATCH of Python envs.

    Inference modes: ``"device"`` (default) runs one jitted batched
    forward per timestep on this process's local device — the Sebulba
    actor-device path; ``"host"`` loops the numpy forward per env,
    bit-identical to ``ppo.EnvRunner`` at batch 1 (the IMPALA parity
    path).  Parameters arrive either by direct ``set_params`` call or
    by ``StageChannel`` broadcast into this process's mailbox, adopted
    at the next unroll boundary (``params_version`` tags every
    trajectory so the learner can bound staleness).
    """

    def __init__(self, index: int, env_maker_payload: bytes, num_envs: int,
                 seed: int, params: Dict[str, np.ndarray], version: int,
                 inference: str = "device", channel_tag: str = ""):
        from ray_tpu.core.serialization import loads_function

        maker = loads_function(env_maker_payload)
        self.index = index
        self.envs = [maker() for _ in range(num_envs)]
        # Decorrelate env reset streams (env 0 keeps the maker's own
        # seeding — the B=1 parity path must match EnvRunner exactly).
        for j, env in enumerate(self.envs[1:], start=1):
            if hasattr(env, "rng"):
                env.rng = np.random.default_rng((seed + 1) * 100003 + j)
        self.rng = np.random.default_rng(seed)
        self.obs = np.stack([env.reset() for env in self.envs])
        self.episode_return = np.zeros(num_envs, np.float64)
        self.completed_returns: List[float] = []
        self.params = {k: np.asarray(v) for k, v in params.items()}
        self.version = int(version)
        self.inference = inference
        self._edge = f"{channel_tag}:params->{index}"
        self._fwd = None
        if inference == "device":
            import jax

            from ..ppo import _policy_forward

            self._fwd = jax.jit(_policy_forward)

    def address(self) -> str:
        from ray_tpu.collective.p2p import StageChannel

        return StageChannel.self_address()

    def set_params(self, params: Dict[str, np.ndarray], version: int):
        if int(version) > self.version:
            self.params = {k: np.asarray(v) for k, v in params.items()}
            self.version = int(version)
        return self.version

    def _poll_params(self) -> None:
        """Adopt the newest broadcast parameters, if any landed."""
        from ray_tpu.collective.p2p import local_mailbox
        from ray_tpu.core.serialization import SerializedPayload

        latest = local_mailbox().try_take_latest(self._edge)
        if latest is None:
            return
        _seq, value = latest
        if type(value) is SerializedPayload:
            value = value.deserialize()
        version, params = value
        if int(version) > self.version:
            self.params = {k: np.asarray(v) for k, v in params.items()}
            self.version = int(version)

    def _forward_batch(self, obs):
        """(B, obs) -> (logits (B, A), values (B,)) on the local device
        (one batched inference request per timestep) or via the shared
        numpy forward (``ppo._np_policy_forward``)."""
        if self._fwd is not None:
            logits, values = self._fwd(self.params, obs)
            return np.asarray(logits), np.asarray(values)
        from ..ppo import _np_policy_forward

        return _np_policy_forward(self.params, obs)

    def run_unroll(self, num_steps: int) -> Dict[str, Any]:
        """Sample ``num_steps`` transitions from every env; returns a
        time-major (T, B, ...) trajectory batch tagged with the params
        version that produced it."""
        self._poll_params()
        B = len(self.envs)
        obs_buf, act_buf, rew_buf, done_buf, logp_buf = [], [], [], [], []
        for _ in range(num_steps):
            logits, _values = self._forward_batch(self.obs)
            actions = np.zeros(B, np.int64)
            logps = np.zeros(B, np.float32)
            for j in range(B):
                z = logits[j] - logits[j].max()
                probs = np.exp(z) / np.exp(z).sum()
                actions[j] = int(self.rng.choice(len(probs), p=probs))
                logps[j] = float(np.log(probs[actions[j]] + 1e-12))
            obs_buf.append(self.obs.copy())
            act_buf.append(actions)
            logp_buf.append(logps)
            next_obs = np.empty_like(self.obs)
            rewards = np.zeros(B, np.float32)
            dones = np.zeros(B, bool)
            for j, env in enumerate(self.envs):
                o, r, d, _ = env.step(int(actions[j]))
                rewards[j], dones[j] = r, d
                self.episode_return[j] += r
                if d:
                    self.completed_returns.append(
                        float(self.episode_return[j])
                    )
                    self.episode_return[j] = 0.0
                    o = env.reset()
                next_obs[j] = o
            self.obs = next_obs
            rew_buf.append(rewards)
            done_buf.append(dones)
        _logits, last_values = self._forward_batch(self.obs)
        returns, self.completed_returns = self.completed_returns, []
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int32),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, bool),
            "logp_old": np.asarray(logp_buf, np.float32),
            "last_value": np.asarray(last_values, np.float32),
            "episode_returns": returns,
            "params_version": self.version,
            "env_steps": num_steps * B,
        }


class SebulbaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 2
        self.envs_per_runner = 4
        self.rollout_steps = 64
        self.batches_per_step = 4  # learner updates per train() call
        self.max_staleness = 4  # versions; older trajectories are dropped
        self.queue_capacity = 0  # 0 = 2 * num_env_runners
        self.inference = "device"  # "device" | "host"
        # False = sync: update -> flushed broadcast -> resubmit.  With
        # ONE runner that is staleness 0 by construction (the IMPALA-
        # parity configuration); more runners still carry their already-
        # in-flight unroll one version behind.
        self.pipeline_sampling = True
        self.use_placement = False
        self.max_restarts = -1  # -1 = 2 * num_env_runners + 4
        self.hidden = 32
        self.lr = 3e-3
        self.entropy_coeff = 0.01
        self.value_coeff = 0.5
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0


class Sebulba(Algorithm):
    def setup(self, config: SebulbaConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.collective.p2p import StageChannel

        from ..env import CartPole
        from ..ppo import _init_policy

        maker = config.env_maker or (lambda: CartPole())
        self._maker_payload = dumps_function(maker)
        probe = maker()
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions

        key = jax.random.PRNGKey(config.seed)
        self.params = _init_policy(
            key, self.obs_size, self.num_actions, config.hidden
        )
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        tx = self.tx

        loss_fn = make_vtrace_loss(
            gamma=config.gamma,
            rho_bar=config.vtrace_clip_rho,
            c_bar=config.vtrace_clip_c,
            value_coeff=config.value_coeff,
            entropy_coeff=config.entropy_coeff,
        )

        def batched_update(params, opt_state, batch):
            """V-trace over a (B, T, ...) trajectory batch: the shared
            per-trajectory loss vmapped over the batch axis."""

            def mean_loss(p):
                losses, _aux = jax.vmap(lambda b: loss_fn(p, b))(batch)
                return jnp.mean(losses)

            loss, grads = jax.value_and_grad(mean_loss)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = jax.jit(batched_update)

        self._placement = None
        if config.use_placement:
            from ray_tpu.core.placement import podracer_placement_group

            self._placement = podracer_placement_group(
                num_actor_bundles=config.num_env_runners,
                num_learner_bundles=1,
                name="sebulba",
            )
            self._placement.ready(timeout=60)

        self._version = 0
        self._channel = StageChannel(
            f"sebulba-{os.getpid()}-{id(self):x}", recv_timeout_s=60.0
        )
        self._addresses: Dict[int, str] = {}
        self._queue: deque = deque()
        self._stale_dropped = 0

        max_restarts = config.max_restarts
        if max_restarts is not None and max_restarts < 0:
            max_restarts = 2 * config.num_env_runners + 4
        self.runner_group = FaultTolerantActorManager(
            self._make_runner,
            config.num_env_runners,
            max_restarts=max_restarts,
            on_respawn=self._on_respawn,
            name="sebulba",
        )
        for i in range(config.num_env_runners):
            self.runner_group.submit(
                i, "run_unroll", config.rollout_steps
            )

    # ------------------------------------------------------------- runners
    def _np_params(self):
        return {k: np.asarray(v) for k, v in self.params.items()}

    def _make_runner(self, i: int):
        cfg = self.config
        cls = SebulbaEnvRunner
        if self._placement is not None:
            cls = cls.options(
                scheduling_strategy=self._placement.actor_strategy(i)
            )
        actor = cls.remote(
            i, self._maker_payload, cfg.envs_per_runner, cfg.seed + i,
            self._np_params(), self._version, cfg.inference,
            self._channel.tag,
        )
        try:
            self._addresses[i] = ray_tpu.get(
                actor.address.remote(), timeout=60
            )
        except Exception:  # noqa: BLE001 — broadcast degrades to set_params
            logger.warning("runner %d address fetch failed; "
                           "broadcast will skip it", i)
            self._addresses.pop(i, None)
        return actor

    def _on_respawn(self, i: int, actor) -> None:
        """A replacement runner spawned with CURRENT params — just point
        it back at the sampling loop."""
        self.runner_group.submit(i, "run_unroll", self.config.rollout_steps)

    def _broadcast_params(self, flush: bool) -> None:
        """Serialize once, fan out to every runner's mailbox over the
        zero-copy push path; a dead destination is the manager's problem
        (detected at harvest), not the broadcast's.

        ``flush`` waits for every ack before returning (the sync-mode
        staleness guarantee needs params IN the mailbox before the
        runner is resubmitted).  Pipelined mode skips it — params are
        fresh immutable buffers each version, newest-wins adoption makes
        a late ack harmless, and blocking the learner hot path on every
        runner's ack per update would serialize learning on the slowest
        runner; the channel is drained once per training step instead."""
        from ray_tpu.util import flight_recorder

        destinations = [
            (f"{self._channel.tag}:params->{i}", addr)
            for i, addr in sorted(self._addresses.items())
        ]
        if not destinations:
            return
        value = (self._version, self._np_params())
        try:
            nbytes = self._channel.broadcast(
                self._version, value, destinations, timeout=30.0
            )
            if flush:
                self._channel.flush(timeout=30.0)
            flight_recorder.record_rl_broadcast(nbytes, len(destinations))
        except Exception as e:  # noqa: BLE001 — dead runner mid-broadcast
            logger.warning("param broadcast v%d partially failed: %s",
                           self._version, e)

    # ------------------------------------------------------------- learner
    def _consume_trajectory(self, traj, stats: Dict[str, Any]):
        """Staleness gate + one batched v-trace update + broadcast.
        Returns the loss, or None if the trajectory was dropped."""
        import jax.numpy as jnp

        from ray_tpu.util import flight_recorder

        cfg = self.config
        staleness = self._version - int(traj["params_version"])
        if staleness > cfg.max_staleness:
            self._stale_dropped += 1
            stats["dropped"] += 1
            flight_recorder.record_rl_stale_dropped("sebulba")
            return None
        # Consumed-path staleness only: the result dict's staleness_max
        # must agree with the recorder histogram (and with the bound —
        # dropped trajectories are accounted by the dropped counter).
        stats["staleness"].append(staleness)
        # Runner batches are time-major (T, B); the vmapped loss wants
        # the batch axis leading.
        batch = {
            "obs": jnp.swapaxes(jnp.asarray(traj["obs"]), 0, 1),
            "actions": jnp.swapaxes(jnp.asarray(traj["actions"]), 0, 1),
            "rewards": jnp.swapaxes(jnp.asarray(traj["rewards"]), 0, 1),
            "dones": jnp.swapaxes(
                jnp.asarray(traj["dones"], np.float32), 0, 1
            ),
            "logp_old": jnp.swapaxes(jnp.asarray(traj["logp_old"]), 0, 1),
            "last_value": jnp.asarray(traj["last_value"], np.float32),
        }
        self.params, self.opt_state, loss = self._update(
            self.params, self.opt_state, batch
        )
        self._version += 1
        flight_recorder.record_rl_update(
            "sebulba", staleness=staleness, queue_depth=len(self._queue)
        )
        self._broadcast_params(flush=not cfg.pipeline_sampling)
        stats["episode_returns"].extend(traj["episode_returns"])
        stats["env_steps"] += int(traj["env_steps"])
        return loss

    def training_step(self) -> Dict[str, Any]:
        import time as _time

        cfg = self.config
        capacity = cfg.queue_capacity or 2 * cfg.num_env_runners
        stats: Dict[str, Any] = {
            "episode_returns": [], "env_steps": 0, "staleness": [],
            "dropped": 0,
        }
        loss = None
        processed = 0
        restarts_before = self.runner_group.num_replacements
        self.runner_group.new_restart_window()
        t0 = _time.perf_counter()
        while processed < cfg.batches_per_step:
            i, traj = self.runner_group.wait_any(timeout=300)
            if cfg.pipeline_sampling:
                # Resubmit BEFORE the update: the runner samples the
                # next unroll (under current-or-soon params) while the
                # learner works — the Sebulba overlap.  Staleness is the
                # price; the gate below bounds it.
                self.runner_group.submit(i, "run_unroll", cfg.rollout_steps)
            self._queue.append(traj)
            while len(self._queue) > capacity:
                # Oldest-first shedding: over capacity the backlog can
                # only get staler.
                from ray_tpu.util import flight_recorder

                self._queue.popleft()
                self._stale_dropped += 1
                stats["dropped"] += 1
                flight_recorder.record_rl_stale_dropped("sebulba")
            while self._queue and processed < cfg.batches_per_step:
                out = self._consume_trajectory(
                    self._queue.popleft(), stats
                )
                if out is not None:
                    loss = out
                    processed += 1
            if not cfg.pipeline_sampling:
                # Sync mode: the runner only resamples AFTER the fresh
                # params landed (flushed broadcast) — with a single
                # runner that is staleness 0 by construction, the
                # IMPALA-parity configuration (with more runners their
                # already-in-flight unrolls still arrive one version
                # behind).
                self.runner_group.submit(i, "run_unroll", cfg.rollout_steps)
        # Pipelined broadcasts were fire-and-forget; drain the acks once
        # per step so delivery errors still surface (as warnings).
        if cfg.pipeline_sampling:
            try:
                self._channel.flush(timeout=30.0)
            except Exception as e:  # noqa: BLE001 — dead runner's ack
                logger.warning("param broadcast ack drain: %s", e)
        dt = _time.perf_counter() - t0
        from ray_tpu.util import flight_recorder

        flight_recorder.record_rl_rollout(
            "sebulba", stats["env_steps"], dt
        )
        flight_recorder.record_rl_learner_rate(
            "sebulba", processed / max(dt, 1e-9)
        )
        returns = stats["episode_returns"]
        staleness = stats["staleness"]
        return {
            "episode_return_mean": (
                float(np.mean(returns)) if returns else None
            ),
            "num_env_steps_sampled": stats["env_steps"],
            "loss": float(loss) if loss is not None else None,
            "num_learner_updates": processed,
            "learner_steps_per_s": processed / max(dt, 1e-9),
            "params_version": self._version,
            "staleness_mean": (
                float(np.mean(staleness)) if staleness else 0.0
            ),
            "staleness_max": int(max(staleness)) if staleness else 0,
            "num_stale_trajs_dropped": stats["dropped"],
            "num_runner_restarts": (
                self.runner_group.num_replacements - restarts_before
            ),
            "queue_depth": len(self._queue),
        }

    # ------------------------------------------------------------ lifecycle
    def get_state(self) -> Dict[str, Any]:
        return {"params": self._np_params(), "version": self._version}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = self.tx.init(self.params)
        # The version is MONOTONIC across restores: runners adopt only
        # newer versions, so restoring an old checkpoint must re-issue
        # the restored params under a version ABOVE anything a live
        # runner holds — otherwise every broadcast would be rejected and
        # the fleet would keep sampling the pre-restore policy (with
        # negative staleness sailing through the gate).
        self._version = max(
            self._version, int(state.get("version", 0))
        ) + 1
        np_params = self._np_params()
        for i, actor in enumerate(self.runner_group.actors):
            try:
                actor.set_params.remote(np_params, self._version)
            except Exception as e:  # noqa: BLE001 — dead runner: the
                # manager respawns it with current params at harvest.
                logger.warning("set_state push to runner %d failed: %s",
                               i, e)

    def cleanup(self) -> None:
        self.runner_group.kill_all()
        if self._placement is not None:
            try:
                self._placement.remove()
            except Exception:  # noqa: BLE001 — shutdown best-effort
                logger.info("podracer placement group removal failed "
                            "(cluster already down?)")


SebulbaConfig.ALGO_CLS = Sebulba
