"""DreamerV3 — model-based RL: learn a world model, act in imagination.

Reference: ray ``rllib/algorithms/dreamerv3/`` (TF implementation of
Hafner et al. 2023).  TPU-first redesign, not a port: the world model,
imagination rollout, and both optimizers are pure JAX ``lax.scan``
programs under one jit each — the imagined trajectories never leave the
device — while env runners stay CPU actors (same split as every other
algorithm here).

Faithful pieces: RSSM with categorical latents (straight-through
gradients), KL balancing with free bits (beta_dyn/beta_rep), symlog
observation/reward regression, continue head, lambda-return targets, and
return-normalized actor advantages.  Documented simplifications vs the
paper: MLP encoder/decoder only (vector observations), MSE-on-symlog
instead of two-hot distributional heads, REINFORCE gradients for both
discrete and continuous actors, and a plain ring sequence buffer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig


# ------------------------------------------------------------------ helpers
def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _mlp_init(key, sizes, scale_last=1.0):
    import jax

    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (fi, fo) in enumerate(zip(sizes[:-1], sizes[1:])):
        s = scale_last if i == len(sizes) - 2 else (2.0 / fi) ** 0.5
        params.append({
            "w": jax.random.normal(keys[i], (fi, fo)) * s,
            "b": np.zeros(fo, np.float32),
        })
    return params


def _mlp(params, x):
    import jax.numpy as jnp

    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.where(x > 0, x, 0.01 * x)  # leaky relu
    return x


def _gru_init(key, in_size, hidden):
    import jax

    k1, k2 = jax.random.split(key)
    s = (1.0 / (in_size + hidden)) ** 0.5
    return {
        "wi": jax.random.normal(k1, (in_size, 3 * hidden)) * s,
        "wh": jax.random.normal(k2, (hidden, 3 * hidden)) * s,
        "b": np.zeros(3 * hidden, np.float32),
    }


def _gru(params, h, x):
    import jax
    import jax.numpy as jnp

    gates = x @ params["wi"] + h @ params["wh"] + params["b"]
    r, u, c = jnp.split(gates, 3, axis=-1)
    r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
    c = jnp.tanh(r * c)
    return u * h + (1 - u) * c


@dataclasses.dataclass
class _Hyper:
    deter: int = 64          # GRU state size
    stoch: int = 8           # categorical latent variables
    classes: int = 8         # classes per latent
    hidden: int = 64
    seq_len: int = 16
    batch_size: int = 8
    horizon: int = 8         # imagination length
    gamma: float = 0.985
    lam: float = 0.95
    free_bits: float = 1.0
    beta_dyn: float = 0.5
    beta_rep: float = 0.1
    entropy: float = 3e-3
    wm_lr: float = 3e-3
    ac_lr: float = 1e-3
    buffer_capacity: int = 20_000
    min_buffer: int = 512
    train_ratio: int = 4     # WM/AC updates per train() call
    num_env_runners: int = 1
    rollout_steps: int = 256
    seed: int = 0


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.hp = _Hyper()
        for k, v in dataclasses.asdict(self.hp).items():
            setattr(self, k, v)

    def training(self, **kwargs) -> "DreamerV3Config":
        super().training(**kwargs)
        for f in dataclasses.fields(_Hyper):
            setattr(self.hp, f.name, getattr(self, f.name))
        return self

    def debugging(self, seed: int = 0) -> "DreamerV3Config":
        super().debugging(seed)
        self.hp.seed = seed
        return self

    def env_runners(self, n, rollout_steps=None) -> "DreamerV3Config":
        super().env_runners(n, rollout_steps)
        self.hp.num_env_runners = n
        if rollout_steps is not None:
            self.hp.rollout_steps = rollout_steps
        return self


# ------------------------------------------------------------- world model
def init_world_model(key, hp: _Hyper, obs_size: int, action_size: int):
    import jax

    zdim = hp.stoch * hp.classes
    ks = jax.random.split(key, 7)
    return {
        "enc": _mlp_init(ks[0], [obs_size, hp.hidden, hp.hidden]),
        "gru": _gru_init(ks[1], zdim + action_size, hp.deter),
        "prior": _mlp_init(ks[2], [hp.deter, hp.hidden, zdim]),
        "post": _mlp_init(ks[3], [hp.deter + hp.hidden, hp.hidden, zdim]),
        "dec": _mlp_init(ks[4], [hp.deter + zdim, hp.hidden, obs_size]),
        "rew": _mlp_init(ks[5], [hp.deter + zdim, hp.hidden, 1], 0.01),
        "cont": _mlp_init(ks[6], [hp.deter + zdim, hp.hidden, 1], 0.01),
    }


def _sample_latent(key, logits, hp: _Hyper):
    """Straight-through one-hot sample of the categorical latents."""
    import jax
    import jax.numpy as jnp

    logits = logits.reshape(logits.shape[:-1] + (hp.stoch, hp.classes))
    # Unimix: 1% uniform mixing (paper) keeps KL finite.
    probs = 0.99 * jax.nn.softmax(logits) + 0.01 / hp.classes
    logits = jnp.log(probs)
    idx = jax.random.categorical(key, logits)
    one_hot = jax.nn.one_hot(idx, hp.classes)
    st = one_hot + jax.nn.softmax(logits) - jax.lax.stop_gradient(
        jax.nn.softmax(logits)
    )
    return st.reshape(st.shape[:-2] + (hp.stoch * hp.classes,)), logits


def _kl(lhs_logits, rhs_logits):
    """KL(lhs || rhs) summed over latents, mean over batch dims."""
    import jax
    import jax.numpy as jnp

    lp, lq = jax.nn.log_softmax(lhs_logits), jax.nn.log_softmax(rhs_logits)
    p = jnp.exp(lp)
    return (p * (lp - lq)).sum(-1).sum(-1)


def make_wm_loss(hp: _Hyper):
    import jax
    import jax.numpy as jnp

    def wm_loss(wm, key, obs, actions, is_first):
        """obs [B,T,O]; actions [B,T,A] (a_{t} taken AT t); is_first [B,T].
        Returns loss + posterior features for imagination starts."""
        B, T = obs.shape[:2]
        zdim = hp.stoch * hp.classes
        embed = _mlp(wm["enc"], symlog(obs))  # [B,T,H]
        keys = jax.random.split(key, T)

        def step(carry, xs):
            h, z = carry
            emb_t, act_prev, first_t, k = xs
            # Episode boundary: reset recurrent + latent state.
            mask = (1.0 - first_t)[:, None]
            h, z = h * mask, z * mask
            act_prev = act_prev * mask
            h = _gru(wm["gru"], h, jnp.concatenate([z, act_prev], -1))
            prior_logits = _mlp(wm["prior"], h)
            post_in = jnp.concatenate([h, emb_t], -1)
            post_logits = _mlp(wm["post"], post_in)
            z, post_l = _sample_latent(k, post_logits, hp)
            prior_l = prior_logits.reshape(
                prior_logits.shape[:-1] + (hp.stoch, hp.classes)
            )
            prior_l = jnp.log(
                0.99 * jax.nn.softmax(prior_l) + 0.01 / hp.classes
            )
            return (h, z), (h, z, post_l, prior_l)

        h0 = jnp.zeros((B, hp.deter))
        z0 = jnp.zeros((B, zdim))
        # a_{t-1} feeds step t: shift actions right by one.
        act_prev = jnp.concatenate(
            [jnp.zeros_like(actions[:, :1]), actions[:, :-1]], 1
        )
        (_, _), (hs, zs, post_l, prior_l) = jax.lax.scan(
            step, (h0, z0),
            (embed.swapaxes(0, 1), act_prev.swapaxes(0, 1),
             is_first.swapaxes(0, 1), keys),
        )
        hs, zs = hs.swapaxes(0, 1), zs.swapaxes(0, 1)  # [B,T,·]
        post_l, prior_l = post_l.swapaxes(0, 1), prior_l.swapaxes(0, 1)
        feat = jnp.concatenate([hs, zs], -1)
        obs_hat = _mlp(wm["dec"], feat)
        rew_hat = _mlp(wm["rew"], feat)[..., 0]
        cont_hat = _mlp(wm["cont"], feat)[..., 0]
        return feat, obs_hat, rew_hat, cont_hat, post_l, prior_l

    def loss_fn(wm, key, batch):
        obs, actions = batch["obs"], batch["actions"]
        rewards, dones = batch["rewards"], batch["dones"]
        is_first = batch["is_first"]
        feat, obs_hat, rew_hat, cont_hat, post_l, prior_l = wm_loss(
            wm, key, obs, actions, is_first
        )
        pred = (
            ((obs_hat - symlog(obs)) ** 2).sum(-1)
            + (rew_hat - symlog(rewards)) ** 2
        ).mean()
        cont = -(
            (1.0 - dones) * jax.nn.log_sigmoid(cont_hat)
            + dones * jax.nn.log_sigmoid(-cont_hat)
        ).mean()
        sg = jax.lax.stop_gradient
        dyn = jnp.maximum(_kl(sg(post_l), prior_l), hp.free_bits).mean()
        rep = jnp.maximum(_kl(post_l, sg(prior_l)), hp.free_bits).mean()
        loss = pred + cont + hp.beta_dyn * dyn + hp.beta_rep * rep
        return loss, (feat, {"wm_loss": loss, "pred": pred,
                             "kl_dyn": dyn, "kl_rep": rep})

    return loss_fn


# ------------------------------------------------------ actor-critic heads
def init_actor_critic(key, hp: _Hyper, action_size: int, discrete: bool):
    import jax

    feat = hp.deter + hp.stoch * hp.classes
    k1, k2 = jax.random.split(key)
    out = action_size if discrete else 2 * action_size
    return {
        "actor": _mlp_init(k1, [feat, hp.hidden, out], 0.01),
        "critic": _mlp_init(k2, [feat, hp.hidden, 1], 0.01),
    }


def make_ac_update(hp: _Hyper, discrete: bool, action_size: int):
    import jax
    import jax.numpy as jnp

    def policy(ac, key, feat):
        out = _mlp(ac["actor"], feat)
        if discrete:
            a = jax.random.categorical(key, out)
            logp = jax.nn.log_softmax(out)[
                jnp.arange(out.shape[0]), a
            ]
            ent = -(jax.nn.softmax(out) * jax.nn.log_softmax(out)).sum(-1)
            return jax.nn.one_hot(a, action_size), logp, ent
        mean, log_std = jnp.split(out, 2, -1)
        log_std = jnp.clip(log_std, -5.0, 1.0)
        eps = jax.random.normal(key, mean.shape)
        a = jnp.tanh(mean + eps * jnp.exp(log_std))
        logp = (
            -0.5 * (eps ** 2 + 2 * log_std + np.log(2 * np.pi))
            - jnp.log1p(-a ** 2 + 1e-6)
        ).sum(-1)
        ent = (log_std + 0.5 * np.log(2 * np.pi * np.e)).sum(-1)
        return a, logp, ent

    def imagine(wm, ac, key, feat0):
        """Roll the prior forward under the actor for hp.horizon steps."""
        zdim = hp.stoch * hp.classes
        h, z = feat0[:, :hp.deter], feat0[:, hp.deter:]

        def step(carry, k):
            h, z = carry
            ka, kz = jax.random.split(k)
            feat = jnp.concatenate([h, z], -1)
            a, logp, ent = policy(ac, ka, feat)
            h2 = _gru(wm["gru"], h, jnp.concatenate([z, a], -1))
            z2, _ = _sample_latent(kz, _mlp(wm["prior"], h2), hp)
            feat2 = jnp.concatenate([h2, z2], -1)
            rew = symexp(_mlp(wm["rew"], feat2)[..., 0])
            cont = jax.nn.sigmoid(_mlp(wm["cont"], feat2)[..., 0])
            return (h2, z2), (feat, feat2, logp, ent, rew, cont)

        keys = jax.random.split(key, hp.horizon)
        _, traj = jax.lax.scan(step, (h, z), keys)
        return traj  # time-major [H, N, ...]

    def lambda_returns(rew, cont, values):
        """values aligned with feat2 (post-step states); returns [H,N]."""
        disc = cont * hp.gamma

        def back(acc, xs):
            r, d, v = xs
            ret = r + d * ((1 - hp.lam) * v + hp.lam * acc)
            return ret, ret

        last = values[-1]
        _, rets = jax.lax.scan(
            back, last, (rew, disc, values), reverse=True
        )
        return rets

    def update(wm, ac, key, feat0, ret_std):
        sg = jax.lax.stop_gradient

        def ac_loss(ac):
            traj = imagine(sg(wm), ac, key, feat0)
            feat, feat2, logp, ent, rew, cont = traj
            values = _mlp(ac["critic"], feat2)[..., 0]
            values_se = symexp(values)
            rets = lambda_returns(rew, cont, sg(values_se))
            # Return normalization (paper: scale by S = EMA of the return
            # spread); advantage = (ret - v) / max(1, S).
            adv = sg((rets - values_se) / jnp.maximum(1.0, ret_std))
            # Discount weights so later imagined steps count less once a
            # predicted episode end passed.
            weights = sg(jnp.cumprod(
                jnp.concatenate([jnp.ones_like(cont[:1]), cont[:-1]], 0),
                0,
            ))
            actor = -(weights * (logp * adv + hp.entropy * ent)).mean()
            critic = (
                weights * (_mlp(ac["critic"], sg(feat2))[..., 0]
                           - sg(symlog(rets))) ** 2
            ).mean()
            new_std = rets.std() + 1e-6
            return actor + critic, (rets.mean(), new_std)

        (loss, (ret_mean, new_std)), grads = jax.value_and_grad(
            ac_loss, has_aux=True
        )(ac)
        return loss, grads, ret_mean, new_std

    return policy, imagine, update


# ------------------------------------------------------------- env runner
@ray_tpu.remote
class _DreamerRunner:
    """CPU env actor: acts through the world model's posterior filter
    (encoder + GRU) with the broadcast params snapshot."""

    def __init__(self, env_payload, hp: _Hyper, obs_size, action_size,
                 discrete, runner_idx):
        from ray_tpu.core.serialization import loads_function

        self.env = loads_function(env_payload)()
        self.hp = hp
        self.discrete = discrete
        self.action_size = action_size
        self.idx = runner_idx
        self.obs = self.env.reset()
        self.h = np.zeros(hp.deter, np.float32)
        self.z = np.zeros(hp.stoch * hp.classes, np.float32)
        self.prev_action = np.zeros(action_size, np.float32)
        self.first = True
        self._t = 0
        self.episode_return = 0.0
        self.completed: list = []
        self._act = None

    def _build(self):
        import jax
        import jax.numpy as jnp

        hp = self.hp
        _, _, _ = hp.deter, hp.stoch, hp.classes
        from .dreamerv3 import (  # self-import: jit closures
            _gru, _mlp, _sample_latent, make_ac_update, symlog,
        )

        policy, _, _ = make_ac_update(hp, self.discrete, self.action_size)

        def act(wm, ac, key, obs, h, z, a_prev, first):
            kz, ka = jax.random.split(key)  # distinct draws: latent/action
            mask = 1.0 - first
            h, z, a_prev = h * mask, z * mask, a_prev * mask
            h = _gru(
                wm["gru"], h[None], jnp.concatenate([z, a_prev])[None]
            )[0]
            emb = _mlp(wm["enc"], symlog(obs))
            post = _mlp(wm["post"], jnp.concatenate([h, emb]))
            z, _ = _sample_latent(kz, post[None], hp)
            z = z[0]
            a, _, _ = policy(ac, ka, jnp.concatenate([h, z])[None])
            return a[0], h, z

        self._act = jax.jit(act)

    def sample(self, wm, ac, n_steps, random_actions=False):
        import jax

        if self._act is None:
            self._build()
        rng = np.random.default_rng((self.hp.seed, self.idx, self._t))
        base = jax.random.fold_in(
            jax.random.PRNGKey(self.hp.seed), self.idx
        )
        rows = {k: [] for k in
                ("obs", "actions", "rewards", "dones", "is_first")}
        for _ in range(n_steps):
            if random_actions:
                if self.discrete:
                    a = np.zeros(self.action_size, np.float32)
                    a[rng.integers(self.action_size)] = 1.0
                else:
                    a = rng.uniform(-1, 1, self.action_size).astype(
                        np.float32
                    )
            else:
                key = jax.random.fold_in(base, self._t)
                a, h, z = self._act(
                    wm, ac, key,
                    np.asarray(self.obs, np.float32),
                    self.h, self.z, self.prev_action,
                    np.float32(self.first),
                )
                a = np.asarray(a, np.float32)
                self.h, self.z = np.asarray(h), np.asarray(z)
            env_a = int(np.argmax(a)) if self.discrete else a * getattr(
                self.env, "action_high", 1.0
            )
            next_obs, reward, done, _ = self.env.step(env_a)
            rows["obs"].append(np.asarray(self.obs, np.float32))
            rows["actions"].append(a)
            rows["rewards"].append(np.float32(reward))
            rows["dones"].append(np.float32(done))
            rows["is_first"].append(np.float32(self.first))
            self.first = False
            self.prev_action = a
            self.episode_return += reward
            self._t += 1
            if done:
                self.completed.append(self.episode_return)
                self.episode_return = 0.0
                self.obs = self.env.reset()
                self.first = True
                self.h = np.zeros_like(self.h)
                self.z = np.zeros_like(self.z)
                self.prev_action = np.zeros_like(self.prev_action)
            else:
                self.obs = next_obs
        eps, self.completed = self.completed, []
        return {k: np.asarray(v) for k, v in rows.items()}, eps


# ----------------------------------------------------------------- buffer
class SequenceBuffer:
    """Flat ring of transitions; samples fixed-length windows (episode
    boundaries handled by the stored is_first flags, paper-style)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._data: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(batch["obs"])
        if self._data is None:
            self._data = {
                k: np.zeros((self.capacity,) + np.asarray(v).shape[1:],
                            np.asarray(v).dtype)
                for k, v in batch.items()
            }
        for i in range(n):  # ring-write row by row (n << capacity)
            for k, v in batch.items():
                self._data[k][self._next] = v[i]
            self._next = (self._next + 1) % self.capacity
            self._size = min(self._size + 1, self.capacity)

    def __len__(self):
        return self._size

    def sample(self, batch_size: int, seq_len: int) -> Dict[str, np.ndarray]:
        starts = self._rng.integers(
            0, self._size - seq_len, size=batch_size
        )
        out = {
            k: np.stack([v[s:s + seq_len] for s in starts])
            for k, v in self._data.items()
        }
        # A window that straddles the ring's write head or an episode cut
        # is still trainable: is_first resets the RSSM state mid-window.
        out["is_first"][:, 0] = 1.0
        return out


# -------------------------------------------------------------- algorithm
class DreamerV3(Algorithm):
    def setup(self, config: DreamerV3Config):
        import jax
        import optax
        from ray_tpu.core.serialization import dumps_function

        hp = self.hp = config.hp
        env_maker = config.env_maker
        if env_maker is None:
            from .env import Pendulum

            env_maker = Pendulum
        probe = env_maker()
        self.obs_size = probe.observation_size
        self.discrete = hasattr(probe, "num_actions")
        self.action_size = (
            probe.num_actions if self.discrete else probe.action_size
        )
        key = jax.random.PRNGKey(hp.seed)
        k_wm, k_ac, self._key = jax.random.split(key, 3)
        self.wm = init_world_model(k_wm, hp, self.obs_size, self.action_size)
        self.ac = init_actor_critic(
            k_ac, hp, self.action_size, self.discrete
        )
        self.wm_tx = optax.chain(
            optax.clip_by_global_norm(100.0), optax.adam(hp.wm_lr)
        )
        self.ac_tx = optax.chain(
            optax.clip_by_global_norm(100.0), optax.adam(hp.ac_lr)
        )
        self.wm_opt = self.wm_tx.init(self.wm)
        self.ac_opt = self.ac_tx.init(self.ac)
        self.ret_std = np.float32(1.0)

        wm_loss = make_wm_loss(hp)
        _, _, ac_update = make_ac_update(hp, self.discrete, self.action_size)

        def train_once(wm, ac, wm_opt, ac_opt, key, batch, ret_std):
            k1, k2 = jax.random.split(key)
            (wml, (feat, metrics)), wm_grads = jax.value_and_grad(
                wm_loss, has_aux=True
            )(wm, k1, batch)
            up, wm_opt = self.wm_tx.update(wm_grads, wm_opt, wm)
            wm = optax.apply_updates(wm, up)
            feat0 = jax.lax.stop_gradient(
                feat.reshape(-1, feat.shape[-1])
            )
            acl, ac_grads, ret_mean, new_std = ac_update(
                wm, ac, k2, feat0, ret_std
            )
            up, ac_opt = self.ac_tx.update(ac_grads, ac_opt, ac)
            ac = optax.apply_updates(ac, up)
            metrics = dict(metrics)
            metrics.update(ac_loss=acl, imag_return=ret_mean)
            return wm, ac, wm_opt, ac_opt, new_std, metrics

        self._train_once = jax.jit(train_once)
        self.buffer = SequenceBuffer(hp.buffer_capacity, seed=hp.seed)
        env_payload = dumps_function(env_maker)
        self.runners = [
            _DreamerRunner.remote(
                env_payload, hp, self.obs_size, self.action_size,
                self.discrete, i,
            )
            for i in range(max(1, hp.num_env_runners))
        ]
        self._episode_returns: list = []
        self._total_steps = 0

    def training_step(self) -> Dict[str, Any]:
        import jax

        hp = self.hp
        random_phase = len(self.buffer) < hp.min_buffer
        refs = [
            r.sample.remote(self.wm, self.ac, hp.rollout_steps, random_phase)
            for r in self.runners
        ]
        for batch, eps in ray_tpu.get(refs, timeout=600):
            self.buffer.add_batch(batch)
            self._episode_returns.extend(eps)
            self._total_steps += len(batch["obs"])
        metrics: Dict[str, Any] = {}
        if len(self.buffer) >= hp.min_buffer:
            for _ in range(hp.train_ratio):
                self._key, sub = jax.random.split(self._key)
                batch = self.buffer.sample(hp.batch_size, hp.seq_len)
                (self.wm, self.ac, self.wm_opt, self.ac_opt,
                 new_std, metrics) = self._train_once(
                    self.wm, self.ac, self.wm_opt, self.ac_opt,
                    sub, batch, self.ret_std,
                )
                # EMA of the imagined-return spread (normalizer).
                self.ret_std = 0.99 * self.ret_std + 0.01 * float(new_std)
        recent = self._episode_returns[-20:]
        return {
            "total_steps": self._total_steps,
            "buffer_size": len(self.buffer),
            "episode_return_mean": (
                float(np.mean(recent)) if recent else None
            ),
            **{k: float(v) for k, v in metrics.items()},
        }

    def get_state(self):
        return {
            "wm": self.wm, "ac": self.ac,
            "wm_opt": self.wm_opt, "ac_opt": self.ac_opt,
            "ret_std": self.ret_std,
        }

    def set_state(self, state):
        self.wm = state["wm"]
        self.ac = state["ac"]
        self.wm_opt = state["wm_opt"]
        self.ac_opt = state["ac_opt"]
        self.ret_std = state["ret_std"]

    def cleanup(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass


DreamerV3Config.ALGO_CLS = DreamerV3
