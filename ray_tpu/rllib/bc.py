"""Behavior Cloning — offline RL from a dataset of (obs, action) pairs.

Reference: ray ``rllib/algorithms/bc/`` (+ ``rllib/offline/``): supervised
cross-entropy on logged actions, reading batches through the Data layer.
MARWIL reduces to this when advantages are all-ones (``beta=0``); passing
``beta>0`` weights the loss by exponentiated advantages, giving the MARWIL
objective.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig, init_mlp, mlp_forward

_N_LAYERS = 2


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.hidden = 64
        self.train_batch_size = 256
        self.num_sgd_steps = 16
        self.beta = 0.0  # >0 → MARWIL advantage weighting
        self.offline_data = None  # ray_tpu.data.Dataset or dict of arrays

    def offline(self, data) -> "BCConfig":
        self.offline_data = data
        return self


class BC(Algorithm):
    def setup(self, config: BCConfig) -> None:
        import jax
        import optax

        data = config.offline_data
        if data is None:
            raise ValueError("BC requires .offline(data)")
        if not isinstance(data, dict):  # a Dataset of {"obs","actions",...}
            rows = data.take_all()
            data = {
                k: np.asarray([r[k] for r in rows])
                for k in rows[0].keys()
            }
        self.data = {
            "obs": np.asarray(data["obs"], np.float32),
            "actions": np.asarray(data["actions"], np.int64),
            "advantages": np.asarray(
                data.get(
                    "advantages", np.ones(len(data["actions"]), np.float32)
                ),
                np.float32,
            ),
        }
        obs_size = self.data["obs"].shape[1]
        num_actions = int(self.data["actions"].max()) + 1
        self.num_actions = num_actions

        key = jax.random.PRNGKey(config.seed)
        self.params = init_mlp(key, [obs_size, config.hidden, num_actions])
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.default_rng(config.seed)
        beta = config.beta
        tx = self.tx

        def update(params, opt_state, batch):
            import jax.numpy as jnp

            def loss_fn(p):
                logits = mlp_forward(p, batch["obs"], _N_LAYERS)
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, batch["actions"][:, None], axis=1
                )[:, 0]
                weight = (
                    jnp.exp(beta * batch["advantages"]) if beta > 0 else 1.0
                )
                return -jnp.mean(weight * logp)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax as _optax

            return _optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        n = len(self.data["actions"])
        loss = None
        for _ in range(cfg.num_sgd_steps):
            idx = self._rng.integers(0, n, size=min(cfg.train_batch_size, n))
            batch = {k: jnp.asarray(v[idx]) for k, v in self.data.items()}
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch
            )
        return {"loss": float(loss)}

    def compute_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp

        logits = mlp_forward(self.params, jnp.asarray(obs), _N_LAYERS)
        return int(np.argmax(np.asarray(logits)))

    def get_state(self) -> Dict[str, Any]:
        return {"params": {k: np.asarray(v) for k, v in self.params.items()}}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = self.tx.init(self.params)


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0


class MARWIL(BC):
    pass


BCConfig.ALGO_CLS = BC
MARWILConfig.ALGO_CLS = MARWIL
