"""Fault-tolerant management of a homogeneous group of worker actors.

Reference: ``FaultTolerantActorManager`` (ray
``rllib/utils/actor_manager.py``): issue calls to all actors, harvest
results with a timeout, mark/replace the dead so one lost sampler never
stalls training.

Two harvest shapes:

- ``foreach`` — synchronous broadcast round (DQN's sampling barrier).
- ``submit`` / ``wait_any`` — one in-flight call per actor, harvest
  whichever finishes first (the IMPALA/Sebulba async core).  A dead or
  stalled actor is detected at harvest, killed, respawned (bounded by
  ``max_restarts`` so a deterministic failure cannot respawn forever),
  and handed to ``on_respawn`` so the caller can resubmit it with fresh
  state — the wait itself never stalls on the corpse.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class FaultTolerantActorManager:
    def __init__(
        self,
        make_actor: Callable[[int], Any],
        num_actors: int,
        restore: bool = True,
        max_restarts: Optional[int] = None,
        on_respawn: Optional[Callable[[int, Any], None]] = None,
        name: str = "",
    ):
        """``make_actor(index) -> ActorHandle``; ``restore`` controls whether
        dead actors are transparently replaced at harvest time.
        ``max_restarts`` bounds replacements per restart WINDOW (None =
        unbounded) — callers open a new window each training step via
        ``new_restart_window()``, so occasional deaths over a long run
        are absorbed indefinitely while a fast crash-loop (a
        deterministic failure respawning within one step) still trips
        the budget; ``on_respawn(index, actor)`` runs after each
        replacement (typical use: resubmit work with current params);
        ``name`` tags the restart metric."""
        self._make_actor = make_actor
        self._restore = restore
        self._max_restarts = max_restarts
        self._on_respawn = on_respawn
        self._name = name or "actor_group"
        self.actors: List[Any] = [make_actor(i) for i in range(num_actors)]
        self.num_replacements = 0
        self._window_replacements = 0
        self._inflight: Dict[int, Any] = {}

    def __len__(self) -> int:
        return len(self.actors)

    # ---------------------------------------------------- broadcast round
    def foreach(
        self,
        method: str,
        *args,
        timeout: float = 300.0,
        **kwargs,
    ) -> List[Tuple[int, Any]]:
        """Call ``method`` on every actor; returns [(index, result)] for the
        healthy ones.  ``timeout`` bounds the whole round (a shared
        deadline, not per-actor).  Dead/stalled actors are killed and
        replaced."""
        refs = [
            (i, getattr(actor, method).remote(*args, **kwargs))
            for i, actor in enumerate(self.actors)
        ]
        return self._harvest(refs, timeout)

    def _harvest(self, refs, timeout: float) -> List[Tuple[int, Any]]:
        import time

        deadline = time.monotonic() + timeout
        out: List[Tuple[int, Any]] = []
        for i, ref in refs:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                out.append((i, ray_tpu.get(ref, timeout=remaining)))
            except Exception as e:  # noqa: BLE001
                self._replace(i, e)
        return out

    # ------------------------------------------------- async one-in-flight
    def submit(self, index: int, method: str, *args, **kwargs) -> None:
        """Issue ``method`` on actor ``index`` (one in-flight per slot —
        a second submit before harvest replaces the tracked ref)."""
        self._inflight[index] = getattr(
            self.actors[index], method
        ).remote(*args, **kwargs)

    def wait_any(self, timeout: float = 300.0) -> Tuple[int, Any]:
        """Block until ANY in-flight call completes successfully; returns
        ``(index, result)`` with the slot's in-flight entry cleared.

        A call that completed with an error means a dead/failed actor:
        it is killed, respawned (bounded), ``on_respawn`` runs, and the
        wait continues over the remaining in-flight set — one corpse
        never stalls the harvest.  Raises TimeoutError if nothing
        completes before the deadline and RuntimeError once the restart
        budget is exhausted."""
        import time

        deadline = time.monotonic() + timeout
        while True:
            if not self._inflight:
                raise RuntimeError(
                    f"{self._name}: wait_any with no in-flight calls "
                    "(submit work first, or every actor died with "
                    "on_respawn not resubmitting)"
                )
            idx_by_ref = {ref: i for i, ref in self._inflight.items()}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"{self._name}: no actor completed within {timeout:.0f}s"
                )
            ready, _ = ray_tpu.wait(
                list(idx_by_ref), num_returns=1, timeout=remaining
            )
            if not ready:
                raise TimeoutError(
                    f"{self._name}: no actor completed within {timeout:.0f}s"
                )
            i = idx_by_ref[ready[0]]
            try:
                result = ray_tpu.get(ready[0], timeout=60)
            except Exception as e:  # noqa: BLE001 — dead actor: replace
                del self._inflight[i]
                self._replace(i, e)
                continue
            del self._inflight[i]
            return i, result

    def inflight_count(self) -> int:
        return len(self._inflight)

    def new_restart_window(self) -> None:
        """Open a fresh restart-budget window (call at the top of each
        training step): ``max_restarts`` bounds respawns per window,
        not per group lifetime."""
        self._window_replacements = 0

    # ------------------------------------------------------- replacement
    def _replace(self, i: int, error: Exception) -> None:
        logger.warning(
            "%s actor %d failed (%s)%s", self._name, i, error,
            "; replacing" if self._restore else "",
        )
        if not self._restore:
            return
        # Kill the old handle FIRST — even on the budget-exhausted path:
        # a stalled (not dead) actor would otherwise leak its process +
        # resource slot exactly when the caller is about to give up.
        try:
            ray_tpu.kill(self.actors[i])
        except Exception:
            pass
        if (
            self._max_restarts is not None
            and self._window_replacements >= self._max_restarts
        ):
            raise RuntimeError(
                f"{self._name}: actor {i} failed and the restart budget "
                f"({self._max_restarts} per window) is exhausted; "
                f"last error: {error}"
            ) from error
        self.actors[i] = self._make_actor(i)
        self.num_replacements += 1
        self._window_replacements += 1
        from ray_tpu.util import flight_recorder

        flight_recorder.record_rl_runner_restart(self._name)
        if self._on_respawn is not None:
            self._on_respawn(i, self.actors[i])

    def kill_all(self) -> None:
        for actor in self.actors:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        self.actors = []
        self._inflight = {}
