"""Fault-tolerant management of a homogeneous group of worker actors.

Reference: ``FaultTolerantActorManager`` (ray
``rllib/utils/actor_manager.py``): issue calls to all actors, harvest
results with a timeout, mark/replace the dead so one lost sampler never
stalls training.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class FaultTolerantActorManager:
    def __init__(
        self,
        make_actor: Callable[[int], Any],
        num_actors: int,
        restore: bool = True,
    ):
        """``make_actor(index) -> ActorHandle``; ``restore`` controls whether
        dead actors are transparently replaced at harvest time."""
        self._make_actor = make_actor
        self._restore = restore
        self.actors: List[Any] = [make_actor(i) for i in range(num_actors)]
        self.num_replacements = 0

    def __len__(self) -> int:
        return len(self.actors)

    def foreach(
        self,
        method: str,
        *args,
        timeout: float = 300.0,
        **kwargs,
    ) -> List[Tuple[int, Any]]:
        """Call ``method`` on every actor; returns [(index, result)] for the
        healthy ones.  ``timeout`` bounds the whole round (a shared
        deadline, not per-actor).  Dead/stalled actors are killed and
        replaced."""
        refs = [
            (i, getattr(actor, method).remote(*args, **kwargs))
            for i, actor in enumerate(self.actors)
        ]
        return self._harvest(refs, timeout)

    def _harvest(self, refs, timeout: float) -> List[Tuple[int, Any]]:
        import time

        deadline = time.monotonic() + timeout
        out: List[Tuple[int, Any]] = []
        for i, ref in refs:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                out.append((i, ray_tpu.get(ref, timeout=remaining)))
            except Exception as e:  # noqa: BLE001
                logger.warning("actor %d failed (%s)%s", i, e,
                               "; replacing" if self._restore else "")
                if self._restore:
                    # Kill the old handle first: a stalled (not dead) actor
                    # would otherwise leak its process + resource slot.
                    try:
                        ray_tpu.kill(self.actors[i])
                    except Exception:
                        pass
                    self.actors[i] = self._make_actor(i)
                    self.num_replacements += 1
        return out

    def kill_all(self) -> None:
        for actor in self.actors:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        self.actors = []
