"""RLModule — the model abstraction of the learner stack.

Reference: ray ``rllib/core/rl_module/rl_module.py`` (+
``multi_rl_module.py``): one object owns the neural nets and exposes three
forward passes — inference (greedy/deterministic), exploration (sampling),
train (everything the loss needs) — so algorithms, env runners, and
learners share a single model definition.

TPU-first redesign: an RLModule here is a *stateless* bundle of pure
functions over an explicit params pytree (init/forwards), so every forward
jits and shards like any other JAX function and params ship to env-runner
actors as plain arrays — no module pickling, no framework wrappers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass
class RLModuleSpec:
    """Builds an RLModule (reference ``RLModuleSpec.build``)."""

    module_class: type
    model_config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def build(self, obs_size: int, action_size: int) -> "RLModule":
        return self.module_class(obs_size, action_size, **self.model_config)


class RLModule:
    """Pure-function model bundle.  Subclasses define the architecture."""

    def __init__(self, obs_size: int, action_size: int, **model_config):
        self.obs_size = obs_size
        self.action_size = action_size
        self.model_config = model_config

    # -- params ------------------------------------------------------------
    def init_state(self, key) -> Dict[str, Any]:
        raise NotImplementedError

    # -- forwards (pure; jit-safe) ------------------------------------------
    def forward_inference(self, params, batch) -> Dict[str, Any]:
        """Deterministic outputs for serving/eval."""
        raise NotImplementedError

    def forward_exploration(self, params, batch, key) -> Dict[str, Any]:
        """Sampling outputs for env runners."""
        raise NotImplementedError

    def forward_train(self, params, batch) -> Dict[str, Any]:
        """Everything the loss needs (logits, values, q-values, …)."""
        raise NotImplementedError


def _mlp_init(key, sizes, out_scale=0.01):
    import jax

    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        scale = (2.0 / fan_in) ** 0.5 if i < len(sizes) - 2 else out_scale
        params[f"w{i}"] = jax.random.normal(keys[i], (fan_in, fan_out)) * scale
        params[f"b{i}"] = jax.numpy.zeros((fan_out,))
    return params


def _mlp_apply(params, x, n_layers, activation="tanh"):
    import jax
    import jax.numpy as jnp

    act = {"tanh": jnp.tanh, "relu": jax.nn.relu}[activation]
    for i in range(n_layers):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            x = act(x)
    return x


class DiscretePolicyModule(RLModule):
    """Categorical policy + value head over a shared MLP torso (the default
    module shape PPO/IMPALA-style algorithms consume)."""

    def init_state(self, key):
        import jax

        hidden = self.model_config.get("hidden", 64)
        k1, k2 = jax.random.split(key)
        return {
            "pi": _mlp_init(k1, [self.obs_size, hidden, self.action_size]),
            "vf": _mlp_init(k2, [self.obs_size, hidden, 1], out_scale=1.0),
        }

    def _heads(self, params, obs):
        logits = _mlp_apply(params["pi"], obs, 2)
        value = _mlp_apply(params["vf"], obs, 2)[..., 0]
        return logits, value

    def forward_inference(self, params, batch):
        import jax.numpy as jnp

        logits, value = self._heads(params, batch["obs"])
        return {"actions": jnp.argmax(logits, -1), "logits": logits,
                "vf_preds": value}

    def forward_exploration(self, params, batch, key):
        import jax

        logits, value = self._heads(params, batch["obs"])
        actions = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)
        import jax.numpy as jnp

        action_logp = jnp.take_along_axis(
            logp, actions[:, None], axis=1
        )[:, 0]
        return {"actions": actions, "logits": logits, "vf_preds": value,
                "action_logp": action_logp}

    def forward_train(self, params, batch):
        logits, value = self._heads(params, batch["obs"])
        return {"logits": logits, "vf_preds": value}


class SACModule(RLModule):
    """Tanh-squashed gaussian policy + twin Q networks (reference
    ``rllib/algorithms/sac/``'s default RLModule, JAX-native)."""

    LOG_STD_MIN, LOG_STD_MAX = -10.0, 2.0

    def init_state(self, key):
        import jax

        hidden = self.model_config.get("hidden", 64)
        k1, k2, k3 = jax.random.split(key, 3)
        qin = self.obs_size + self.action_size
        return {
            "pi": _mlp_init(
                k1, [self.obs_size, hidden, hidden, 2 * self.action_size]
            ),
            "q1": _mlp_init(k2, [qin, hidden, hidden, 1], out_scale=1.0),
            "q2": _mlp_init(k3, [qin, hidden, hidden, 1], out_scale=1.0),
        }

    def _pi(self, params, obs):
        import jax.numpy as jnp

        out = _mlp_apply(params["pi"], obs, 3, activation="relu")
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mean, log_std

    def sample_action(self, params, obs, key):
        """Reparameterized tanh-gaussian sample with squash-corrected
        log-prob."""
        import jax
        import jax.numpy as jnp

        mean, log_std = self._pi(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        action = jnp.tanh(pre)
        logp = (
            -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
        ).sum(-1)
        # tanh change-of-variables correction
        logp = logp - jnp.log(1 - action ** 2 + 1e-6).sum(-1)
        return action, logp

    def q_values(self, params, obs, actions):
        import jax.numpy as jnp

        x = jnp.concatenate([obs, actions], axis=-1)
        q1 = _mlp_apply(params["q1"], x, 3, activation="relu")[..., 0]
        q2 = _mlp_apply(params["q2"], x, 3, activation="relu")[..., 0]
        return q1, q2

    def forward_inference(self, params, batch):
        import jax.numpy as jnp

        mean, _ = self._pi(params, batch["obs"])
        return {"actions": jnp.tanh(mean)}

    def forward_exploration(self, params, batch, key):
        actions, logp = self.sample_action(params, batch["obs"], key)
        return {"actions": actions, "action_logp": logp}

    def forward_train(self, params, batch):
        q1, q2 = self.q_values(params, batch["obs"], batch["actions"])
        return {"q1": q1, "q2": q2}


class MultiRLModule:
    """module_id -> RLModule (+ per-module params) — the multi-agent
    surface (reference ``multi_rl_module.py``)."""

    def __init__(self, modules: Dict[str, RLModule]):
        self.modules = dict(modules)

    def init_state(self, key):
        import jax

        keys = jax.random.split(key, len(self.modules))
        return {
            mid: m.init_state(k)
            for (mid, m), k in zip(sorted(self.modules.items()), keys)
        }

    def __getitem__(self, module_id: str) -> RLModule:
        return self.modules[module_id]

    def keys(self):
        return self.modules.keys()
