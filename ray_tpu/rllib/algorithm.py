"""Algorithm base + config builder.

Reference: ``Algorithm`` (ray ``rllib/algorithms/algorithm.py:212`` — a
Tune Trainable whose ``step()`` runs one sample+learn iteration) and the
``AlgorithmConfig`` fluent builder (``rllib/algorithms/algorithm_config.py``).
TPU-first: learners are jitted JAX updates (single chip here; a slice via a
``data``-sharded mesh), env runners stay CPU actors.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional

import numpy as np


class AlgorithmConfig:
    """Fluent builder: ``.environment(...).env_runners(...).training(...)``."""

    ALGO_CLS: Optional[type] = None

    def __init__(self):
        self.env_maker: Optional[Callable] = None
        self.num_env_runners: int = 2
        self.rollout_steps: int = 256
        self.gamma: float = 0.99
        self.lr: float = 3e-3
        self.seed: int = 0

    def environment(self, env_maker: Callable) -> "AlgorithmConfig":
        self.env_maker = env_maker
        return self

    def env_runners(
        self, num_env_runners: int, rollout_steps: Optional[int] = None
    ) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        if rollout_steps is not None:
            self.rollout_steps = rollout_steps
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k) or callable(getattr(self, k)):
                # Fail loudly: a swallowed typo is a silently wrong run.
                raise ValueError(
                    f"unknown training option {k!r} for "
                    f"{type(self).__name__}"
                )
            setattr(self, k, v)
        return self

    def debugging(self, seed: int = 0) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def build(self) -> "Algorithm":
        assert self.ALGO_CLS is not None, "config has no bound algorithm"
        return self.ALGO_CLS(self)


class Algorithm:
    """Train/save/restore lifecycle (Tune-Trainable-compatible: pass
    ``lambda config: algo.train()`` style loops, or use directly)."""

    def __init__(self, config: AlgorithmConfig):
        from ray_tpu.core.usage import record_library_usage

        record_library_usage("rllib")
        self.config = config
        self.iteration = 0
        self.setup(config)

    # -- subclass surface ---------------------------------------------------
    def setup(self, config: AlgorithmConfig) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    def cleanup(self) -> None:
        pass

    # -- public lifecycle ---------------------------------------------------
    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        result = self.training_step()
        result.setdefault("training_iteration", self.iteration)
        return result

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        state = {"iteration": self.iteration, "state": self.get_state()}
        with open(path, "wb") as f:
            pickle.dump(state, f)
        return path

    def restore(self, checkpoint_path: str) -> None:
        if os.path.isdir(checkpoint_path):
            checkpoint_path = os.path.join(
                checkpoint_path, "algorithm_state.pkl"
            )
        with open(checkpoint_path, "rb") as f:
            state = pickle.load(f)
        self.iteration = state["iteration"]
        self.set_state(state["state"])

    def stop(self) -> None:
        self.cleanup()


# --------------------------------------------------------- shared mlp module
def init_mlp(key, sizes, out_scale: float = 0.01):
    """He-init MLP params; final layer near-zero (policy/Q head)."""
    import jax

    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        last = i == len(sizes) - 2
        scale = out_scale if last else (2.0 / fan_in) ** 0.5
        params[f"w{i}"] = jax.random.normal(keys[i], (fan_in, fan_out)) * scale
        params[f"b{i}"] = np.zeros(fan_out, np.float32)
    return params


def mlp_forward(params, x, n_layers: int):
    import jax.numpy as jnp

    for i in range(n_layers):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            x = jnp.tanh(x)
    return x


def mlp_forward_np(params, x, n_layers: int):
    """Numpy twin for CPU env runners (no jax import in samplers)."""
    for i in range(n_layers):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n_layers - 1:
            x = np.tanh(x)
    return x
