"""PPO — algorithm + JAX learner + distributed env runners.

Reference architecture (ray ``rllib/algorithms/algorithm.py:212``,
``env/env_runner_group.py:70``, ``core/learner/learner_group.py:101``): the
Algorithm coordinates an EnvRunnerGroup of sampling actors and a Learner
performing SGD.  TPU-first differences: the policy/value nets and the PPO
update are pure-JAX jitted functions (the learner step runs on the chip; on
a slice the same update jits over a device mesh with batch sharded on
``data``); env runners stay CPU actors that receive broadcast params each
iteration — sampling scales with actors, learning scales with chips.
Fault tolerance: dead runners are detected at poll time and replaced
(the FaultTolerantActorManager pattern, ray ``rllib/utils/actor_manager.py``).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.core.serialization import dumps_function

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class PPOConfig:
    env_maker: Any = None  # callable () -> env; default CartPole
    num_env_runners: int = 2
    rollout_steps: int = 256  # per runner per iteration
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-3
    entropy_coeff: float = 0.01
    value_coeff: float = 0.5
    num_sgd_epochs: int = 4
    minibatch_size: int = 128
    hidden: int = 32
    seed: int = 0

    def build(self) -> "PPO":
        return PPO(self)


# ----------------------------------------------------------------- learner
def _init_policy(key, obs_size: int, num_actions: int, hidden: int):
    import jax

    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale = lambda fan_in: (2.0 / fan_in) ** 0.5
    return {
        "w1": jax.random.normal(k1, (obs_size, hidden)) * scale(obs_size),
        "b1": np.zeros(hidden, np.float32),
        "wp": jax.random.normal(k2, (hidden, num_actions)) * 0.01,
        "bp": np.zeros(num_actions, np.float32),
        "wv": jax.random.normal(k3, (hidden, 1)) * scale(hidden),
        "bv": np.zeros(1, np.float32),
    }


def _policy_forward(params, obs):
    import jax.numpy as jnp

    h = jnp.tanh(obs @ params["w1"] + params["b1"])
    logits = h @ params["wp"] + params["bp"]
    value = (h @ params["wv"] + params["bv"])[..., 0]
    return logits, value


def _np_policy_forward(params, obs):
    """Numpy twin of ``_policy_forward`` for host-side samplers (no jax
    import): EnvRunner, the Sebulba host-inference path, and
    ``evaluate_policy_numpy`` ALL call this one function — the
    bit-identical-parity claims between them are pinned on there being
    exactly one copy of this math.  ``obs`` may be a single observation
    (``(obs,)``) or a batch (``(B, obs)``); values follow the leading
    shape."""
    h = np.tanh(obs @ params["w1"] + params["b1"])
    logits = h @ params["wp"] + params["bp"]
    values = (h @ params["wv"] + params["bv"])[..., 0]
    return logits, values


class JaxLearner:
    """Jitted PPO update (clipped surrogate + value + entropy)."""

    def __init__(self, cfg: PPOConfig, obs_size: int, num_actions: int):
        import jax
        import optax

        self.cfg = cfg
        key = jax.random.PRNGKey(cfg.seed)
        self.params = _init_policy(key, obs_size, num_actions, cfg.hidden)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)

        clip_eps = cfg.clip_eps
        vf, ent = cfg.value_coeff, cfg.entropy_coeff

        def loss_fn(params, batch):
            import jax.numpy as jnp

            logits, value = _policy_forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            surrogate = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv,
            )
            value_loss = jnp.mean((value - batch["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            )
            loss = -jnp.mean(surrogate) + vf * value_loss - ent * entropy
            return loss, {
                "policy_loss": -jnp.mean(surrogate),
                "value_loss": value_loss,
                "entropy": entropy,
            }

        def update(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = self.tx.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            stats["total_loss"] = loss
            return params, opt_state, stats

        self._update = jax.jit(update)

    def update_minibatches(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        n = len(batch["obs"])
        rng = np.random.default_rng(self.cfg.seed)
        stats = {}
        mb = min(self.cfg.minibatch_size, n)
        for _ in range(self.cfg.num_sgd_epochs):
            perm = rng.permutation(n)
            for i in range(0, n - mb + 1, mb):
                idx = perm[i : i + mb]
                sub = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self.params, self.opt_state, st = self._update(
                    self.params, self.opt_state, sub
                )
                stats = st
        return {k: float(v) for k, v in stats.items()}

    def get_params(self):
        import jax

        return jax.tree.map(np.asarray, self.params)


# -------------------------------------------------------------- env runner
@ray_tpu.remote
class EnvRunner:
    """Sampling actor: rolls out the current policy in its env copy."""

    def __init__(self, env_maker_payload: bytes, seed: int):
        from ray_tpu.core.serialization import loads_function

        maker = loads_function(env_maker_payload)
        self.env = maker()
        self.rng = np.random.default_rng(seed)
        self.obs = self.env.reset()
        self.episode_return = 0.0
        self.completed_returns: List[float] = []

    def sample(self, params: Dict[str, np.ndarray], num_steps: int):
        """CPU numpy forward (tiny policy net) — no jax import needed."""
        obs_buf, act_buf, rew_buf, done_buf, logp_buf, val_buf = (
            [], [], [], [], [], [],
        )
        for _ in range(num_steps):
            logits, value = _np_policy_forward(params, self.obs)
            logits = logits - logits.max()
            probs = np.exp(logits) / np.exp(logits).sum()
            action = int(self.rng.choice(len(probs), p=probs))
            value = float(value)
            obs_buf.append(self.obs)
            act_buf.append(action)
            logp_buf.append(float(np.log(probs[action] + 1e-12)))
            val_buf.append(value)
            self.obs, reward, done, _ = self.env.step(action)
            rew_buf.append(reward)
            done_buf.append(done)
            self.episode_return += reward
            if done:
                self.completed_returns.append(self.episode_return)
                self.episode_return = 0.0
                self.obs = self.env.reset()
        # Bootstrap value for the unfinished tail.
        _, last_value = _np_policy_forward(params, self.obs)
        last_value = float(last_value)
        returns, self.completed_returns = self.completed_returns, []
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int32),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, bool),
            "logp_old": np.asarray(logp_buf, np.float32),
            "values": np.asarray(val_buf, np.float32),
            "last_value": last_value,
            "episode_returns": returns,
        }


def _compute_gae(traj, gamma: float, lam: float):
    rewards, values, dones = traj["rewards"], traj["values"], traj["dones"]
    n = len(rewards)
    adv = np.zeros(n, np.float32)
    last_gae = 0.0
    next_value = traj["last_value"]
    for t in reversed(range(n)):
        nonterminal = 0.0 if dones[t] else 1.0
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns


# ---------------------------------------------------------------- algorithm
class PPO:
    def __init__(self, config: Optional[PPOConfig] = None):
        from .env import CartPole

        self.config = config or PPOConfig()
        maker = self.config.env_maker or (lambda: CartPole())
        self._maker_payload = dumps_function(maker)
        probe = maker()
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.learner = JaxLearner(self.config, self.obs_size, self.num_actions)
        self.runners = [
            self._make_runner(i) for i in range(self.config.num_env_runners)
        ]
        self.iteration = 0

    def _make_runner(self, idx: int):
        return EnvRunner.remote(self._maker_payload, self.config.seed + idx)

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        params = self.learner.get_params()
        refs = [
            (i, r.sample.remote(params, self.config.rollout_steps))
            for i, r in enumerate(self.runners)
        ]
        trajs = []
        episode_returns: List[float] = []
        for i, ref in refs:
            try:
                trajs.append(ray_tpu.get(ref, timeout=300))
            except Exception as e:  # noqa: BLE001 - replace dead runner
                logger.warning("env runner %d failed (%s); replacing", i, e)
                self.runners[i] = self._make_runner(i)
        if not trajs:
            raise RuntimeError("all env runners failed")
        adv_list, ret_list = [], []
        for t in trajs:
            adv, ret = _compute_gae(
                t, self.config.gamma, self.config.gae_lambda
            )
            adv_list.append(adv)
            ret_list.append(ret)
            episode_returns.extend(t["episode_returns"])
        batch = {
            "obs": np.concatenate([t["obs"] for t in trajs]),
            "actions": np.concatenate([t["actions"] for t in trajs]),
            "logp_old": np.concatenate([t["logp_old"] for t in trajs]),
            "advantages": np.concatenate(adv_list),
            "returns": np.concatenate(ret_list),
        }
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        stats = self.learner.update_minibatches(batch)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(episode_returns)) if episode_returns else None
            ),
            "num_env_steps_sampled": sum(len(t["obs"]) for t in trajs),
            **stats,
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
