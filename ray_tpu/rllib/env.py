"""Built-in environments (gym-API compatible, zero external deps).

The RL workload for BASELINE.md north-star config #3 is PPO; CartPole is the
standard smoke env.  Implemented in numpy with the classic dynamics so tests
run anywhere.
"""

from __future__ import annotations

import numpy as np


class CartPole:
    """Classic cart-pole balancing; observation (4,), actions {0, 1}."""

    observation_size = 4
    num_actions = 2

    def __init__(self, seed: int = 0, max_steps: int = 200):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.state = None
        self.steps = 0

    def reset(self):
        self.state = self.rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self.steps = 0
        return self.state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masspole + self.masscart
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self.steps += 1
        done = bool(
            abs(x) > self.x_threshold
            or abs(theta) > self.theta_threshold
            or self.steps >= self.max_steps
        )
        return self.state.copy(), 1.0, done, {}


class Pendulum:
    """Classic pendulum swing-up — the continuous-control smoke env
    (observation (3,), one action in [-2, 2])."""

    observation_size = 3
    action_size = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, seed: int = 0, max_steps: int = 200):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.g, self.m, self.l, self.dt = 10.0, 1.0, 1.0, 0.05
        self.state = None
        self.steps = 0

    def _obs(self):
        th, thdot = self.state
        return np.array([np.cos(th), np.sin(th), thdot], np.float32)

    def reset(self):
        self.state = np.array(
            [self.rng.uniform(-np.pi, np.pi), self.rng.uniform(-1.0, 1.0)]
        )
        self.steps = 0
        return self._obs()

    def step(self, action):
        th, thdot = self.state
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (
            3 * self.g / (2 * self.l) * np.sin(th)
            + 3.0 / (self.m * self.l ** 2) * u
        ) * self.dt
        thdot = float(np.clip(thdot, -8.0, 8.0))
        th = th + thdot * self.dt
        self.state = np.array([th, thdot])
        self.steps += 1
        done = self.steps >= self.max_steps
        return self._obs(), -cost, done, {}
