"""Built-in environments (gym-API compatible, zero external deps).

The RL workload for BASELINE.md north-star config #3 is PPO; CartPole is the
standard smoke env.  Implemented in numpy with the classic dynamics so tests
run anywhere.

Two families live here:

- ``CartPole`` / ``Pendulum`` — stateful numpy envs for host-side
  env-runner actors (PPO/IMPALA/Sebulba samplers; no jax import).
- ``CartPoleJax`` / ``PendulumJax`` — functional pure-jax twins with
  identical dynamics, written so ``reset``/``step`` trace cleanly under
  ``jit``/``vmap``/``scan``.  These are what the Anakin trainer steps
  *on the accelerator*: thousands of env instances batched over an env
  axis inside one compiled rollout+learn loop (Podracer, arxiv
  2104.06272).  ``step`` auto-resets: the returned state/obs belong to a
  fresh episode whenever ``done`` is True, while ``reward``/``done``
  always describe the transition that just happened (the standard
  gymnax/Anakin convention — a bootstrap value of the post-reset obs is
  harmless because the loss discounts through ``done``).
"""

from __future__ import annotations

import numpy as np


class CartPole:
    """Classic cart-pole balancing; observation (4,), actions {0, 1}."""

    observation_size = 4
    num_actions = 2

    def __init__(self, seed: int = 0, max_steps: int = 200):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.state = None
        self.steps = 0

    def reset(self):
        self.state = self.rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self.steps = 0
        return self.state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masspole + self.masscart
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self.steps += 1
        done = bool(
            abs(x) > self.x_threshold
            or abs(theta) > self.theta_threshold
            or self.steps >= self.max_steps
        )
        return self.state.copy(), 1.0, done, {}


class Pendulum:
    """Classic pendulum swing-up — the continuous-control smoke env
    (observation (3,), one action in [-2, 2])."""

    observation_size = 3
    action_size = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, seed: int = 0, max_steps: int = 200):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.g, self.m, self.l, self.dt = 10.0, 1.0, 1.0, 0.05
        self.state = None
        self.steps = 0

    def _obs(self):
        th, thdot = self.state
        return np.array([np.cos(th), np.sin(th), thdot], np.float32)

    def reset(self):
        self.state = np.array(
            [self.rng.uniform(-np.pi, np.pi), self.rng.uniform(-1.0, 1.0)]
        )
        self.steps = 0
        return self._obs()

    def step(self, action):
        th, thdot = self.state
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (
            3 * self.g / (2 * self.l) * np.sin(th)
            + 3.0 / (self.m * self.l ** 2) * u
        ) * self.dt
        thdot = float(np.clip(thdot, -8.0, 8.0))
        th = th + thdot * self.dt
        self.state = np.array([th, thdot])
        self.steps += 1
        done = self.steps >= self.max_steps
        return self._obs(), -cost, done, {}


# --------------------------------------------------------------- jax twins
class CartPoleJax:
    """Functional pure-jax CartPole with auto-reset.

    State is a pytree ``{"phys": (4,) f32, "steps": () i32}``; ``reset``
    and ``step`` are pure functions of (key, state) so they vmap over an
    env axis and scan over time.  Dynamics are the numpy ``CartPole``'s,
    verbatim — parity is pinned in tests/test_rllib_podracer.py.
    """

    observation_size = 4
    num_actions = 2

    def __init__(self, max_steps: int = 200):
        self.max_steps = max_steps
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4

    def reset(self, key):
        import jax
        import jax.numpy as jnp

        phys = jax.random.uniform(
            key, (4,), jnp.float32, minval=-0.05, maxval=0.05
        )
        state = {"phys": phys, "steps": jnp.zeros((), jnp.int32)}
        return state, phys

    def obs(self, state):
        return state["phys"]

    def step(self, key, state, action):
        import jax.numpy as jnp

        x, x_dot, theta, theta_dot = (
            state["phys"][0], state["phys"][1],
            state["phys"][2], state["phys"][3],
        )
        force = jnp.where(action == 1, self.force_mag, -self.force_mag)
        costheta, sintheta = jnp.cos(theta), jnp.sin(theta)
        total_mass = self.masspole + self.masscart
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        phys = jnp.stack([x, x_dot, theta, theta_dot]).astype(jnp.float32)
        steps = state["steps"] + 1
        done = (
            (jnp.abs(x) > self.x_threshold)
            | (jnp.abs(theta) > self.theta_threshold)
            | (steps >= self.max_steps)
        )
        reset_state, _ = self.reset(key)
        new_state = {
            "phys": jnp.where(done, reset_state["phys"], phys),
            "steps": jnp.where(done, reset_state["steps"], steps),
        }
        reward = jnp.float32(1.0)
        return new_state, new_state["phys"], reward, done

    # Batched-over-an-env-axis views (the Anakin rollout shape).
    def vec_reset(self, key, num_envs: int):
        import jax

        keys = jax.random.split(key, num_envs)
        return jax.vmap(self.reset)(keys)

    def vec_step(self, keys, state, actions):
        import jax

        return jax.vmap(self.step)(keys, state, actions)


class PendulumJax:
    """Functional pure-jax Pendulum swing-up with auto-reset.

    State ``{"phys": (2,) f32 (theta, theta_dot), "steps": () i32}``;
    continuous action clipped to [-2, 2]; episodes truncate at
    ``max_steps`` (the only ``done`` source, matching the numpy env).
    """

    observation_size = 3
    action_size = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, max_steps: int = 200):
        self.max_steps = max_steps
        self.g, self.m, self.l, self.dt = 10.0, 1.0, 1.0, 0.05

    def reset(self, key):
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(key)
        th = jax.random.uniform(
            k1, (), jnp.float32, minval=-np.pi, maxval=np.pi
        )
        thdot = jax.random.uniform(k2, (), jnp.float32, minval=-1.0, maxval=1.0)
        state = {
            "phys": jnp.stack([th, thdot]),
            "steps": jnp.zeros((), jnp.int32),
        }
        return state, self.obs(state)

    def obs(self, state):
        import jax.numpy as jnp

        th, thdot = state["phys"][0], state["phys"][1]
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot])

    def step(self, key, state, action):
        import jax.numpy as jnp

        th, thdot = state["phys"][0], state["phys"][1]
        u = jnp.clip(jnp.reshape(action, (-1,))[0], -2.0, 2.0)
        norm_th = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (
            3 * self.g / (2 * self.l) * jnp.sin(th)
            + 3.0 / (self.m * self.l**2) * u
        ) * self.dt
        thdot = jnp.clip(thdot, -8.0, 8.0)
        th = th + thdot * self.dt
        steps = state["steps"] + 1
        done = steps >= self.max_steps
        reset_state, _ = self.reset(key)
        phys = jnp.stack([th, thdot]).astype(jnp.float32)
        new_state = {
            "phys": jnp.where(done, reset_state["phys"], phys),
            "steps": jnp.where(done, reset_state["steps"], steps),
        }
        return new_state, self.obs(new_state), -cost, done

    def vec_reset(self, key, num_envs: int):
        import jax

        keys = jax.random.split(key, num_envs)
        return jax.vmap(self.reset)(keys)

    def vec_step(self, keys, state, actions):
        import jax

        return jax.vmap(self.step)(keys, state, actions)
