"""Offline RL data pipeline: dataset-backed transition reading.

Reference: ray ``rllib/offline/offline_data.py`` + ``offline_prelearner`` —
offline algorithms read logged transitions through the Data layer
(streaming, shuffled) instead of an env-runner replay buffer.  Sources:
a ``ray_tpu.data.Dataset`` whose rows are transition dicts, a parquet/
json path, or an in-memory dict of column arrays.

Column schema (the SampleBatch subset continuous-control learners need):
``obs``, ``actions``, ``rewards``, ``next_obs``, ``dones``.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Optional

import numpy as np

COLUMNS = ("obs", "actions", "rewards", "next_obs", "dones")


class OfflineData:
    """Shuffled minibatch sampler over an offline transition dataset.

    Streams blocks through ``Dataset.iter_batches`` into a local shuffle
    buffer (the reference's streaming read + local shuffle), re-iterating
    epochs forever; an in-memory dict source samples directly.
    """

    def __init__(self, source, shuffle_buffer_rows: int = 20_000,
                 seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._dataset = None
        self._columns: Optional[Dict[str, np.ndarray]] = None
        if isinstance(source, dict):
            self._columns = {
                k: np.asarray(v) for k, v in source.items()
            }
        elif isinstance(source, str):
            import ray_tpu.data as rd

            self._dataset = (
                rd.read_parquet(source)
                if source.endswith(".parquet") or _has_parquet(source)
                else rd.read_json(source)
            )
        else:
            self._dataset = source  # a ray_tpu.data.Dataset
        self._buffer: Dict[str, np.ndarray] = {}
        self._buffer_rows = 0
        self._shuffle_rows = shuffle_buffer_rows
        self._epoch_iter = None
        # Small datasets end up entirely in the buffer after one epoch:
        # stop streaming then (each refill is distributed work).
        self._epoch_rows = 0
        self._fully_buffered = False

    # ------------------------------------------------------------- sampling
    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        if self._columns is not None:
            n = len(next(iter(self._columns.values())))
            idx = self._rng.integers(0, n, size=batch_size)
            return {k: v[idx] for k, v in self._columns.items()}
        while not self._fully_buffered and self._buffer_rows < max(
            batch_size, self._shuffle_rows // 2
        ):
            if not self._fill_once():
                break
        if self._buffer_rows == 0:
            raise ValueError("offline dataset is empty")
        n = self._buffer_rows
        idx = self._rng.integers(0, n, size=min(batch_size, n))
        return {k: v[:n][idx] for k, v in self._buffer.items()}

    def _fill_once(self) -> bool:
        """Pull one block from the dataset into the shuffle buffer (bounded:
        oldest rows fall out once the buffer is full)."""
        if self._epoch_iter is None:
            self._epoch_iter = self._dataset.iter_batches(
                batch_size=4096, batch_format="numpy"
            )
        try:
            batch = next(self._epoch_iter)
        except StopIteration:
            self._epoch_iter = None  # next fill starts a new epoch
            if self._epoch_rows and self._buffer_rows >= min(
                self._epoch_rows, self._shuffle_rows
            ):
                # The whole dataset (or a full buffer's worth of it) is
                # resident: sampling needs no more distributed reads.
                self._fully_buffered = self._epoch_rows <= self._shuffle_rows
            self._epoch_rows = 0
            return self._buffer_rows > 0
        for k, v in batch.items():
            v = np.asarray(v)
            if v.dtype == object:
                # Parquet list columns (vector obs/actions) come back as
                # object arrays of arrays: re-stack to a 2-D float column.
                v = np.stack([np.asarray(x, np.float32) for x in v])
            cur = self._buffer.get(k)
            self._buffer[k] = v if cur is None else np.concatenate(
                [cur[-self._shuffle_rows:], v]
            )
        self._epoch_rows += len(next(iter(batch.values())))
        self._buffer_rows = min(
            self._shuffle_rows,
            len(next(iter(self._buffer.values()))),
        )
        # Keep the per-column trims aligned.
        for k in self._buffer:
            self._buffer[k] = self._buffer[k][-self._buffer_rows:]
        return True

    def num_rows(self) -> Optional[int]:
        if self._columns is not None:
            return len(next(iter(self._columns.values())))
        try:
            return self._dataset.count()
        except Exception:  # noqa: BLE001
            return None


def record_transitions(
    env_maker: Callable[[], Any],
    policy_fn: Callable[[np.ndarray, random.Random], np.ndarray],
    n_steps: int,
    seed: int = 0,
    parallelism: int = 4,
):
    """Roll a behavior policy and return a ``ray_tpu.data.Dataset`` of
    transitions (the test/offline-generation analog of the reference's
    output writer, ``rllib/offline/json_writer.py``).

    ``policy_fn`` returns actions NORMALIZED to [-1, 1] (the module tanh
    convention the stored dataset uses); the env is stepped with the same
    action rescaled to its ``action_low``/``action_high`` units — the
    exact mapping offline learners' evaluation applies, so training and
    evaluation see identical dynamics."""
    import ray_tpu.data as rd

    env = env_maker()
    lo = float(getattr(env, "action_low", -1.0))
    hi = float(getattr(env, "action_high", 1.0))
    rng = random.Random(seed)
    obs = env.reset()
    rows = []
    for _ in range(n_steps):
        action = np.asarray(policy_fn(obs, rng), np.float32).reshape(-1)
        env_action = lo + (action + 1.0) * 0.5 * (hi - lo)
        next_obs, reward, done, _info = env.step(env_action)
        rows.append(
            {
                "obs": np.asarray(obs, np.float32),
                "actions": action,
                "rewards": np.float32(reward),
                "next_obs": np.asarray(next_obs, np.float32),
                "dones": bool(done),
            }
        )
        obs = env.reset() if done else next_obs
    return rd.from_items(rows, parallelism=parallelism)


def _has_parquet(path: str) -> bool:
    import glob
    import os

    return bool(
        glob.glob(os.path.join(path, "*.parquet"))
        if os.path.isdir(path)
        else path.endswith(".parquet")
    )
