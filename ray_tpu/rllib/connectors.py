"""Connector pipelines — the data-transform layer between env, module,
and learner.

Reference: ray ``rllib/connectors/`` — composable transforms applied
(env→module) before a forward pass on the env runner, (module→env) to the
forward outputs before stepping the env, and (learner) to collected
episodes before the update.  Algorithms assemble default pipelines; users
prepend/append their own connector pieces without touching algorithm code.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform.  ``ctx`` carries episode/batch metadata."""

    def __call__(self, batch: Dict[str, Any], **ctx) -> Dict[str, Any]:
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class ConnectorPipeline(Connector):
    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def __call__(self, batch, **ctx):
        for c in self.connectors:
            batch = c(batch, **ctx)
        return batch

    def prepend(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def append(self, connector: Connector) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def __repr__(self):
        return f"ConnectorPipeline({self.connectors})"


# ------------------------------------------------------------- env → module
class ObsToFloatBatch(Connector):
    """Stack raw observations into a float32 [B, obs] array."""

    def __call__(self, batch, **ctx):
        obs = batch.get("obs")
        arr = np.asarray(obs, np.float32)
        if arr.ndim == 1:
            arr = arr[None]
        return {**batch, "obs": arr}


class NormalizeObs(Connector):
    """Running mean/std observation filter (the MeanStdFilter connector)."""

    def __init__(self, eps: float = 1e-8):
        self.count = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None
        self.eps = eps

    def __call__(self, batch, update: bool = True, **ctx):
        obs = np.asarray(batch["obs"], np.float32)
        flat = obs.reshape(-1, obs.shape[-1])
        if update:
            for row in flat:
                self.count += 1
                if self.mean is None:
                    self.mean = row.copy()
                    self.m2 = np.zeros_like(row)
                else:
                    delta = row - self.mean
                    self.mean += delta / self.count
                    self.m2 += delta * (row - self.mean)
        if self.mean is None or self.count < 2:
            return batch
        std = np.sqrt(self.m2 / max(self.count - 1, 1)) + self.eps
        return {**batch, "obs": (obs - self.mean) / std}


# ------------------------------------------------------------- module → env
class ClipActions(Connector):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, batch, **ctx):
        return {
            **batch,
            "actions": np.clip(
                np.asarray(batch["actions"]), self.low, self.high
            ),
        }


class ScaleActions(Connector):
    """Map [-1, 1] module outputs onto the env's action bounds."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, batch, **ctx):
        a = np.asarray(batch["actions"], np.float32)
        scaled = self.low + (a + 1.0) * 0.5 * (self.high - self.low)
        return {**batch, "actions": scaled}


# ----------------------------------------------------------------- learner
class ComputeGAE(Connector):
    """Generalized advantage estimation over a rollout batch with
    ``vf_preds``/``rewards``/``dones`` (+ bootstrap value)."""

    def __init__(self, gamma: float = 0.99, lam: float = 0.95):
        self.gamma, self.lam = gamma, lam

    def __call__(self, batch, last_value: float = 0.0, **ctx):
        rewards = np.asarray(batch["rewards"], np.float32)
        dones = np.asarray(batch["dones"], bool)
        values = np.asarray(batch["vf_preds"], np.float32)
        n = len(rewards)
        adv = np.zeros(n, np.float32)
        gae = 0.0
        next_value = last_value
        for t in range(n - 1, -1, -1):
            nonterminal = 0.0 if dones[t] else 1.0
            delta = (
                rewards[t] + self.gamma * next_value * nonterminal - values[t]
            )
            gae = delta + self.gamma * self.lam * nonterminal * gae
            adv[t] = gae
            next_value = values[t]
        return {**batch, "advantages": adv, "returns": adv + values}


class NormalizeAdvantages(Connector):
    def __call__(self, batch, **ctx):
        adv = np.asarray(batch["advantages"], np.float32)
        return {
            **batch,
            "advantages": (adv - adv.mean()) / (adv.std() + 1e-8),
        }
