"""Implicit Q-Learning — offline RL without out-of-sample Q queries.

Reference: ray ``rllib/algorithms/iql/`` (expectile-regression IQL):
  - V is trained by expectile regression toward min(Q1, Q2) on DATASET
    actions only (tau > 0.5 biases toward the upper envelope, a soft max
    over in-support actions),
  - Q is trained by Bellman backup toward r + gamma * V(s') (no policy
    actions anywhere in the critic path),
  - the policy is extracted by advantage-weighted regression:
    maximize exp(beta * (Q - V)) * log pi(a_data | s).

Fully offline on ``OfflineData``; actions use the module's normalized
[-1, 1] convention (see ``offline.record_transitions``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .offline import OfflineData
from .rl_module import RLModuleSpec, SACModule, _mlp_apply, _mlp_init


class IQLModule(SACModule):
    """SAC's tanh-gaussian policy + twin Q, plus the state-value net V
    that IQL's expectile regression trains."""

    def init_state(self, key):
        import jax

        params = super().init_state(key)
        hidden = self.model_config.get("hidden", 64)
        kv = jax.random.fold_in(key, 997)
        params["v"] = _mlp_init(
            kv, [self.obs_size, hidden, hidden, 1], out_scale=1.0
        )
        return params

    def v_values(self, params, obs):
        return _mlp_apply(params["v"], obs, 3, activation="relu")[..., 0]


@dataclasses.dataclass
class IQLHyperparams:
    lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005           # polyak for target Q nets
    expectile: float = 0.7       # V regression expectile (tau in the paper)
    beta: float = 3.0            # advantage-weighted regression temperature
    adv_clip: float = 100.0      # exp-weight clip
    hidden: int = 64
    batch_size: int = 256
    learn_steps_per_iter: int = 200
    seed: int = 0


class IQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.hp = IQLHyperparams()
        self.offline_data = None
        self.env_maker: Optional[Callable] = None
        self.rl_module_spec = RLModuleSpec(IQLModule, {})

    def training(self, **kwargs) -> "IQLConfig":
        for k, v in kwargs.items():
            if not hasattr(self.hp, k):
                raise ValueError(f"unknown IQL hyperparam {k!r}")
            setattr(self.hp, k, v)
        return self

    def offline(self, data) -> "IQLConfig":
        self.offline_data = data
        return self

    def environment(self, env_maker) -> "IQLConfig":
        self.env_maker = env_maker
        return self


class IQL(Algorithm):
    def setup(self, config: IQLConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        hp = self.hp = config.hp
        if config.offline_data is None:
            raise ValueError("IQL requires .offline(data)")
        self.data = (
            config.offline_data
            if isinstance(config.offline_data, OfflineData)
            else OfflineData(config.offline_data, seed=hp.seed)
        )
        self.env_maker = config.env_maker
        probe = self.data.sample(2)
        obs_size = probe["obs"].shape[1]
        action_size = probe["actions"].shape[1]

        config.rl_module_spec.model_config.setdefault("hidden", hp.hidden)
        self.module = module = config.rl_module_spec.build(
            obs_size, action_size
        )
        self.params = module.init_state(jax.random.PRNGKey(hp.seed))
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.tx = optax.adam(hp.lr)
        self.opt_state = self.tx.init(self.params)

        gamma, tau = hp.gamma, hp.tau
        expectile, beta, adv_clip = hp.expectile, hp.beta, hp.adv_clip

        def update(params, target_params, opt_state, batch, key):
            import optax as _optax

            obs, acts = batch["obs"], batch["actions"]

            # --- V: expectile regression toward min target-Q(s, a_data)
            tq1, tq2 = module.q_values(target_params, obs, acts)
            q_data = jax.lax.stop_gradient(jnp.minimum(tq1, tq2))

            def v_loss(p):
                v = module.v_values(p, obs)
                diff = q_data - v
                w = jnp.where(diff > 0, expectile, 1.0 - expectile)
                return (w * diff ** 2).mean(), v

            # --- Q: Bellman toward r + gamma * V(s') (dataset actions only)
            next_v = module.v_values(params, batch["next_obs"])
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target_q = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterminal * next_v
            )

            def q_loss(p):
                q1, q2 = module.q_values(p, obs, acts)
                return ((q1 - target_q) ** 2 + (q2 - target_q) ** 2).mean()

            # --- policy: advantage-weighted regression on dataset actions
            def pi_loss(p):
                mean, log_std = module._pi(p, obs)
                std = jnp.exp(log_std)
                # log-prob of the dataset action under the tanh-gaussian
                a = jnp.clip(acts, -1 + 1e-5, 1 - 1e-5)
                pre = jnp.arctanh(a)
                logp = (
                    -0.5 * (((pre - mean) / std) ** 2
                            + 2 * log_std + jnp.log(2 * jnp.pi))
                ).sum(-1)
                logp = logp - jnp.log(1 - a ** 2 + 1e-6).sum(-1)
                v = module.v_values(jax.lax.stop_gradient(p), obs)
                adv = q_data - jax.lax.stop_gradient(v)
                w = jnp.minimum(jnp.exp(beta * adv), adv_clip)
                return -(jax.lax.stop_gradient(w) * logp).mean()

            (vl, _v), vgrads = jax.value_and_grad(v_loss, has_aux=True)(params)
            ql, qgrads = jax.value_and_grad(q_loss)(params)
            pl, pgrads = jax.value_and_grad(pi_loss)(params)
            grads = {
                "pi": pgrads["pi"],
                "q1": qgrads["q1"],
                "q2": qgrads["q2"],
                "v": vgrads["v"],
            }
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = _optax.apply_updates(params, updates)
            target_params = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p, target_params, params
            )
            stats = {"v_loss": vl, "q_loss": ql, "pi_loss": pl}
            return params, target_params, opt_state, stats

        def update_many(params, target_params, opt_state, batches, base_key):
            def body(carry, xs):
                batch, key = xs
                out = update(*carry, batch, key)
                return out[:-1], out[-1]

            n = batches["rewards"].shape[0]
            keys = jax.random.split(base_key, n)
            (params, target_params, opt_state), stats = jax.lax.scan(
                body, (params, target_params, opt_state), (batches, keys)
            )
            return (params, target_params, opt_state,
                    jax.tree.map(lambda s: s[-1], stats))

        self._update_many = jax.jit(update_many)
        self._steps = 0

    _SCAN_CHUNK = 50

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        hp = self.hp
        stats = {}
        remaining = hp.learn_steps_per_iter
        while remaining > 0:
            k = min(self._SCAN_CHUNK, remaining)
            remaining -= k
            sampled = [self.data.sample(hp.batch_size) for _ in range(k)]
            batches = {
                key: jnp.asarray(
                    np.stack([b[key] for b in sampled]),
                    jnp.float32 if key != "dones" else None,
                )
                for key in ("obs", "actions", "rewards", "next_obs", "dones")
            }
            self._steps += k
            key = jax.random.fold_in(jax.random.PRNGKey(hp.seed), self._steps)
            (self.params, self.target_params, self.opt_state,
             stats) = self._update_many(
                self.params, self.target_params, self.opt_state, batches, key,
            )
        out = {k2: float(v) for k2, v in stats.items()}
        out["learn_steps_total"] = self._steps
        return out

    def evaluate(self, episodes: int = 5, seed: int = 100) -> Dict[str, Any]:
        from .cql import CQL

        return CQL.evaluate(self, episodes=episodes, seed=seed)

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {
            "params": jax.tree.map(np.asarray, self.params),
            "target_params": jax.tree.map(np.asarray, self.target_params),
            "steps": self._steps,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = self.tx.init(self.params)
        self._steps = state.get("steps", 0)


IQLConfig.ALGO_CLS = IQL
