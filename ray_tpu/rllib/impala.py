"""IMPALA — asynchronous actor-learner with V-trace off-policy correction.

Reference: ray ``rllib/algorithms/impala/`` (decoupled sampling and
learning: EnvRunners produce trajectories under a stale behavior policy;
the learner corrects with V-trace importance weights).  APPO is this plus a
PPO-style clipped surrogate on the corrected advantages — exposed here via
``APPOConfig`` (``use_appo_clip``).

Async shape: each runner has one in-flight sample at all times; the learner
harvests whichever finishes first, updates, and resubmits that runner with
fresh params — sampling never barriers on the slowest runner.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.core.serialization import dumps_function

from .algorithm import Algorithm, AlgorithmConfig, init_mlp, mlp_forward
from .ppo import EnvRunner  # same on-policy sampler (returns logp_old)

logger = logging.getLogger(__name__)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-3
        self.hidden = 32
        self.rollout_steps = 128
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        self.entropy_coeff = 0.01
        self.value_coeff = 0.5
        self.batches_per_step = 4  # learner updates per train() call
        self.use_appo_clip = False
        self.appo_clip_eps = 0.2


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.use_appo_clip = True


class IMPALA(Algorithm):
    def setup(self, config: IMPALAConfig) -> None:
        import jax
        import optax

        from .env import CartPole
        from .ppo import _init_policy

        maker = config.env_maker or (lambda: CartPole())
        self._maker_payload = dumps_function(maker)
        probe = maker()
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions

        key = jax.random.PRNGKey(config.seed)
        self.params = _init_policy(
            key, self.obs_size, self.num_actions, config.hidden
        )
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)

        gamma = config.gamma
        rho_bar = config.vtrace_clip_rho
        c_bar = config.vtrace_clip_c
        vf, ent = config.value_coeff, config.entropy_coeff
        use_clip, clip_eps = config.use_appo_clip, config.appo_clip_eps
        tx = self.tx

        def vtrace_update(params, opt_state, batch):
            """One V-trace update over a single trajectory (time-major)."""
            import jax.numpy as jnp

            from .ppo import _policy_forward

            def loss_fn(p):
                logits, values = _policy_forward(p, batch["obs"])
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, batch["actions"][:, None], axis=1
                )[:, 0]
                # Importance ratios target/behavior.
                rhos = jnp.exp(logp - batch["logp_old"])
                clipped_rho = jnp.minimum(rho_bar, rhos)
                clipped_c = jnp.minimum(c_bar, rhos)
                discounts = gamma * (1.0 - batch["dones"])
                values_next = jnp.concatenate(
                    [values[1:], batch["last_value"][None]]
                )
                deltas = clipped_rho * (
                    batch["rewards"] + discounts * values_next - values
                )

                def scan_fn(acc, xs):
                    delta, discount, c = xs
                    acc = delta + discount * c * acc
                    return acc, acc

                _, vs_minus_v = jax.lax.scan(
                    scan_fn,
                    jnp.zeros(()),
                    (deltas, discounts, clipped_c),
                    reverse=True,
                )
                vs = jax.lax.stop_gradient(vs_minus_v + values)
                vs_next = jnp.concatenate([vs[1:], batch["last_value"][None]])
                pg_adv = jax.lax.stop_gradient(
                    clipped_rho
                    * (batch["rewards"] + discounts * vs_next - values)
                )
                if use_clip:  # APPO: clipped surrogate on vtrace advantages
                    surrogate = jnp.minimum(
                        rhos * pg_adv,
                        jnp.clip(rhos, 1 - clip_eps, 1 + clip_eps) * pg_adv,
                    )
                    pg_loss = -jnp.mean(surrogate)
                else:
                    pg_loss = -jnp.mean(logp * pg_adv)
                value_loss = jnp.mean((values - vs) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
                )
                loss = pg_loss + vf * value_loss - ent * entropy
                return loss, (pg_loss, value_loss, entropy)

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._vtrace_update = jax.jit(vtrace_update)

        self.runners = [
            EnvRunner.remote(self._maker_payload, config.seed + i)
            for i in range(config.num_env_runners)
        ]
        # One in-flight sample per runner at all times (the async core).
        self._inflight: Dict[int, Any] = {}
        np_params = self._np_params()
        for i, r in enumerate(self.runners):
            self._inflight[i] = r.sample.remote(
                np_params, config.rollout_steps
            )

    def _np_params(self):
        return {k: np.asarray(v) for k, v in self.params.items()}

    def _make_runner(self, i: int):
        return EnvRunner.remote(self._maker_payload, self.config.seed + i)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        episode_returns: List[float] = []
        steps = 0
        loss = None
        processed = 0
        failures = 0
        while processed < cfg.batches_per_step:
            # Harvest whichever runner finishes first.
            refs = list(self._inflight.values())
            idx_by_ref = {ref: i for i, ref in self._inflight.items()}
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=300)
            if not ready:
                raise TimeoutError("no env runner produced a batch in 300s")
            ref = ready[0]
            i = idx_by_ref[ref]
            try:
                traj = ray_tpu.get(ref, timeout=60)
            except Exception as e:  # noqa: BLE001 — replace dead runner
                failures += 1
                if failures > 2 * len(self.runners) + 4:
                    # A deterministic failure (e.g. env_maker unimportable
                    # in workers) would otherwise respawn runners forever.
                    raise RuntimeError(
                        f"env runners keep failing ({failures} in one "
                        f"step); last error: {e}"
                    ) from e
                logger.warning("runner %d failed (%s); replacing", i, e)
                try:
                    ray_tpu.kill(self.runners[i])
                except Exception:
                    pass
                self.runners[i] = self._make_runner(i)
                self._inflight[i] = self.runners[i].sample.remote(
                    self._np_params(), cfg.rollout_steps
                )
                continue
            batch = {
                "obs": jnp.asarray(traj["obs"]),
                "actions": jnp.asarray(traj["actions"]),
                "rewards": jnp.asarray(traj["rewards"]),
                "dones": jnp.asarray(traj["dones"], np.float32),
                "logp_old": jnp.asarray(traj["logp_old"]),
                "last_value": jnp.asarray(traj["last_value"], np.float32),
            }
            self.params, self.opt_state, loss, _aux = self._vtrace_update(
                self.params, self.opt_state, batch
            )
            episode_returns.extend(traj["episode_returns"])
            steps += len(traj["obs"])
            processed += 1
            # Resubmit with fresh params — only this runner, no barrier.
            self._inflight[i] = self.runners[i].sample.remote(
                self._np_params(), cfg.rollout_steps
            )
        return {
            "episode_return_mean": (
                float(np.mean(episode_returns)) if episode_returns else None
            ),
            "num_env_steps_sampled": steps,
            "loss": float(loss) if loss is not None else None,
        }

    def get_state(self) -> Dict[str, Any]:
        return {"params": self._np_params()}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = self.tx.init(self.params)

    def cleanup(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass


class APPO(IMPALA):
    pass


IMPALAConfig.ALGO_CLS = IMPALA
APPOConfig.ALGO_CLS = APPO
