"""IMPALA — asynchronous actor-learner with V-trace off-policy correction.

Reference: ray ``rllib/algorithms/impala/`` (decoupled sampling and
learning: EnvRunners produce trajectories under a stale behavior policy;
the learner corrects with V-trace importance weights).  APPO is this plus a
PPO-style clipped surrogate on the corrected advantages — exposed here via
``APPOConfig`` (``use_appo_clip``).

Async shape: each runner has one in-flight sample at all times; the learner
harvests whichever finishes first, updates, and resubmits that runner with
fresh params — sampling never barriers on the slowest runner.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.core.serialization import dumps_function

from .actor_manager import FaultTolerantActorManager
from .algorithm import Algorithm, AlgorithmConfig, init_mlp, mlp_forward
from .ppo import EnvRunner  # same on-policy sampler (returns logp_old)

logger = logging.getLogger(__name__)


def make_vtrace_loss(
    *,
    gamma: float,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    value_coeff: float = 0.5,
    entropy_coeff: float = 0.01,
    use_appo_clip: bool = False,
    appo_clip_eps: float = 0.2,
):
    """Single-trajectory time-major V-trace actor-critic loss.

    This is THE loss for every actor-learner split in the package:
    IMPALA/APPO use it directly, the podracer trainers reuse it —
    Sebulba vmapped over a trajectory-batch axis (host rollouts under a
    stale behavior policy, rho/c clipping doing the off-policy
    correction), Anakin vmapped over the on-chip env axis (on-policy, so
    the ratios are exactly 1 and it reduces to n-step actor-critic).

    ``batch`` keys: obs (T, obs), actions (T,), rewards (T,), dones
    (T, float), logp_old (T,), last_value () — returns
    ``(loss, (pg_loss, value_loss, entropy))``.
    """

    def loss_fn(params, batch):
        import jax
        import jax.numpy as jnp

        from .ppo import _policy_forward

        logits, values = _policy_forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1
        )[:, 0]
        # Importance ratios target/behavior.
        rhos = jnp.exp(logp - batch["logp_old"])
        clipped_rho = jnp.minimum(rho_bar, rhos)
        clipped_c = jnp.minimum(c_bar, rhos)
        discounts = gamma * (1.0 - batch["dones"])
        values_next = jnp.concatenate([values[1:], batch["last_value"][None]])
        deltas = clipped_rho * (
            batch["rewards"] + discounts * values_next - values
        )

        def scan_fn(acc, xs):
            delta, discount, c = xs
            acc = delta + discount * c * acc
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            scan_fn,
            jnp.zeros(()),
            (deltas, discounts, clipped_c),
            reverse=True,
        )
        vs = jax.lax.stop_gradient(vs_minus_v + values)
        vs_next = jnp.concatenate([vs[1:], batch["last_value"][None]])
        pg_adv = jax.lax.stop_gradient(
            clipped_rho * (batch["rewards"] + discounts * vs_next - values)
        )
        if use_appo_clip:  # APPO: clipped surrogate on vtrace advantages
            surrogate = jnp.minimum(
                rhos * pg_adv,
                jnp.clip(rhos, 1 - appo_clip_eps, 1 + appo_clip_eps) * pg_adv,
            )
            pg_loss = -jnp.mean(surrogate)
        else:
            pg_loss = -jnp.mean(logp * pg_adv)
        value_loss = jnp.mean((values - vs) ** 2)
        entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        loss = pg_loss + value_coeff * value_loss - entropy_coeff * entropy
        return loss, (pg_loss, value_loss, entropy)

    return loss_fn


def make_vtrace_update(tx, loss_fn):
    """value_and_grad + optimizer apply around a v-trace ``loss_fn``.
    Caller jits (IMPALA) or vmaps-then-jits (Sebulba) the result."""

    def update(params, opt_state, batch):
        import jax
        import optax

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, aux

    return update


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-3
        self.hidden = 32
        self.rollout_steps = 128
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        self.entropy_coeff = 0.01
        self.value_coeff = 0.5
        self.batches_per_step = 4  # learner updates per train() call
        self.use_appo_clip = False
        self.appo_clip_eps = 0.2


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.use_appo_clip = True


class IMPALA(Algorithm):
    def setup(self, config: IMPALAConfig) -> None:
        import jax
        import optax

        from .env import CartPole
        from .ppo import _init_policy

        maker = config.env_maker or (lambda: CartPole())
        self._maker_payload = dumps_function(maker)
        probe = maker()
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions

        key = jax.random.PRNGKey(config.seed)
        self.params = _init_policy(
            key, self.obs_size, self.num_actions, config.hidden
        )
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)

        loss_fn = make_vtrace_loss(
            gamma=config.gamma,
            rho_bar=config.vtrace_clip_rho,
            c_bar=config.vtrace_clip_c,
            value_coeff=config.value_coeff,
            entropy_coeff=config.entropy_coeff,
            use_appo_clip=config.use_appo_clip,
            appo_clip_eps=config.appo_clip_eps,
        )
        self._vtrace_update = jax.jit(make_vtrace_update(self.tx, loss_fn))

        # One in-flight sample per runner at all times (the async core);
        # the manager owns liveness: a dead/stalled runner is killed,
        # respawned (bounded budget — a deterministic failure such as an
        # unimportable env_maker must not respawn forever), and
        # resubmitted with current params via on_respawn.
        self.runner_group = FaultTolerantActorManager(
            self._make_runner,
            config.num_env_runners,
            max_restarts=2 * config.num_env_runners + 4,
            on_respawn=self._resubmit,
            name="impala",
        )
        for i in range(config.num_env_runners):
            self._resubmit(i)

    def _np_params(self):
        return {k: np.asarray(v) for k, v in self.params.items()}

    def _make_runner(self, i: int):
        return EnvRunner.remote(self._maker_payload, self.config.seed + i)

    def _resubmit(self, i: int, actor=None) -> None:
        """Issue the next sample for runner ``i`` (also the on_respawn
        hook — a replacement runner starts sampling with fresh params)."""
        self.runner_group.submit(
            i, "sample", self._np_params(), self.config.rollout_steps
        )

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        episode_returns: List[float] = []
        steps = 0
        loss = None
        processed = 0
        restarts_before = self.runner_group.num_replacements
        # Per-step restart budget: transient deaths over a long run are
        # absorbed; a crash-loop within one step still trips it.
        self.runner_group.new_restart_window()
        while processed < cfg.batches_per_step:
            # Harvest whichever runner finishes first; death handling
            # (kill + bounded respawn + resubmit) lives in the manager —
            # the wait never stalls on a dead runner.
            i, traj = self.runner_group.wait_any(timeout=300)
            batch = {
                "obs": jnp.asarray(traj["obs"]),
                "actions": jnp.asarray(traj["actions"]),
                "rewards": jnp.asarray(traj["rewards"]),
                "dones": jnp.asarray(traj["dones"], np.float32),
                "logp_old": jnp.asarray(traj["logp_old"]),
                "last_value": jnp.asarray(traj["last_value"], np.float32),
            }
            self.params, self.opt_state, loss, _aux = self._vtrace_update(
                self.params, self.opt_state, batch
            )
            episode_returns.extend(traj["episode_returns"])
            steps += len(traj["obs"])
            processed += 1
            # Resubmit with fresh params — only this runner, no barrier.
            self._resubmit(i)
        return {
            "episode_return_mean": (
                float(np.mean(episode_returns)) if episode_returns else None
            ),
            "num_env_steps_sampled": steps,
            "loss": float(loss) if loss is not None else None,
            "num_runner_restarts": (
                self.runner_group.num_replacements - restarts_before
            ),
        }

    def get_state(self) -> Dict[str, Any]:
        return {"params": self._np_params()}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.opt_state = self.tx.init(self.params)

    def cleanup(self) -> None:
        self.runner_group.kill_all()


class APPO(IMPALA):
    pass


IMPALAConfig.ALGO_CLS = IMPALA
APPOConfig.ALGO_CLS = APPO
