"""``ray_tpu.rllib`` — reinforcement learning on the core actor runtime.

Reference: ray ``rllib/`` — Algorithm (a Tune Trainable) coordinating env
runner actors for sampling and JAX learners for SGD; algorithms: PPO, DQN
(double/PER), IMPALA/APPO (V-trace), BC/MARWIL (offline).
"""

from .actor_manager import FaultTolerantActorManager  # noqa: F401
from .connectors import (  # noqa: F401
    ClipActions,
    ComputeGAE,
    Connector,
    ConnectorPipeline,
    NormalizeAdvantages,
    NormalizeObs,
    ObsToFloatBatch,
    ScaleActions,
)
from .algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from .bc import BC, BCConfig, MARWIL, MARWILConfig  # noqa: F401
from .dqn import DQN, DQNConfig  # noqa: F401
from .env import CartPole, Pendulum  # noqa: F401
from .impala import (  # noqa: F401
    APPO,
    APPOConfig,
    IMPALA,
    IMPALAConfig,
    make_vtrace_loss,
    make_vtrace_update,
)
from .podracer import (  # noqa: F401
    Anakin,
    AnakinConfig,
    Sebulba,
    SebulbaConfig,
)
from .ppo import PPO, PPOConfig  # noqa: F401
from .replay import PrioritizedReplayBuffer, ReplayBuffer  # noqa: F401
from .rl_module import (  # noqa: F401
    DiscretePolicyModule,
    MultiRLModule,
    RLModule,
    RLModuleSpec,
    SACModule,
)
from .sac import SAC, SACConfig  # noqa: F401
from .offline import OfflineData, record_transitions  # noqa: F401
from .cql import CQL, CQLConfig  # noqa: F401
from .dreamerv3 import DreamerV3, DreamerV3Config  # noqa: F401
from .iql import IQL, IQLConfig, IQLModule  # noqa: F401
from .multi_agent import (  # noqa: F401
    ALL_DONE,
    IndependentTrainer,
    MultiAgentEnv,
    MultiAgentEpisode,
    TwoAgentCoopEnv,
    collect_episodes,
)
