from .env import CartPole  # noqa: F401
from .ppo import PPO, PPOConfig  # noqa: F401
