"""Replay buffers (reference: ray ``rllib/utils/replay_buffers/``)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring buffer over transition dicts of parallel arrays."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._storage: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if self._storage is None:
            self._storage = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()
            }
        if n >= self.capacity:  # keep only the newest capacity rows
            batch = {k: v[n - self.capacity :] for k, v in batch.items()}
            n = self.capacity
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._storage[k][idx] = v
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (simplified PER: power-law probabilities
    over stored TD errors, importance weights returned with each sample)."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, np.float64)
        self._max_priority = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = min(len(next(iter(batch.values()))), self.capacity)
        start = self._next
        super().add_batch(batch)
        idx = (start + np.arange(n)) % self.capacity
        self._priorities[idx] = self._max_priority

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        prios = self._priorities[: self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights /= weights.max()
        out = {k: v[idx] for k, v in self._storage.items()}
        out["_weights"] = weights.astype(np.float32)
        out["_indices"] = idx.astype(np.int64)
        return out

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prios = np.abs(td_errors) + 1e-6
        self._priorities[indices] = prios
        self._max_priority = max(self._max_priority, float(prios.max()))
