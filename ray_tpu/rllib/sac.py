"""SAC — soft actor-critic for continuous control, on the RLModule +
connector architecture.

Reference: ray ``rllib/algorithms/sac/`` (tanh-gaussian policy, twin Q
with target networks, automatic entropy temperature).  TPU-first: the
whole update (actor + twin critics + alpha + polyak) is ONE jitted
function over the replay batch; env runner actors sample with broadcast
params through the connector pipelines (env→module obs batching,
module→env action scaling).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig
from .connectors import (
    ConnectorPipeline,
    ObsToFloatBatch,
    ScaleActions,
)
from .replay import ReplayBuffer
from .rl_module import RLModuleSpec, SACModule

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class _SACHyper:
    gamma: float = 0.99
    tau: float = 0.01  # polyak
    lr: float = 3e-3
    init_alpha: float = 0.1
    target_entropy: Optional[float] = None  # default: -action_size
    buffer_capacity: int = 50_000
    batch_size: int = 128
    rollout_steps: int = 200
    learn_steps_per_iter: int = 64
    warmup_steps: int = 500
    hidden: int = 64
    num_env_runners: int = 1
    seed: int = 0


class SACConfig(AlgorithmConfig):
    ALGO_CLS = None  # filled after SAC is defined

    def __init__(self):
        super().__init__()
        self.hp = _SACHyper()
        self.rl_module_spec = RLModuleSpec(SACModule)

    def training(self, **kwargs) -> "SACConfig":
        for k, v in kwargs.items():
            if hasattr(self.hp, k):
                setattr(self.hp, k, v)
            else:
                super().training(**{k: v})
        return self

    def rl_module(self, spec: RLModuleSpec) -> "SACConfig":
        self.rl_module_spec = spec
        return self


@ray_tpu.remote
class _SACRunner:
    """CPU sampling actor: steps the env with the exploration forward of a
    broadcast RLModule params snapshot, through connector pipelines."""

    def __init__(self, env_payload, spec: RLModuleSpec, seed: int,
                 runner_idx: int, scale_low: float, scale_high: float):
        from ray_tpu.core.serialization import loads_function

        self.env = loads_function(env_payload)()
        self.module = spec.build(
            self.env.observation_size, self.env.action_size
        )
        self.env_to_module = ConnectorPipeline([ObsToFloatBatch()])
        self.module_to_env = ConnectorPipeline(
            [ScaleActions(scale_low, scale_high)]
        )
        self.seed = seed
        # Distinct key stream per runner: fold_in(base, runner_idx) — small
        # additive seed offsets would alias runner i's stream at step t with
        # runner j's at t + offset*(i-j) (correlated exploration noise).
        self.runner_idx = runner_idx
        self._step_count = 0
        self.obs = self.env.reset()
        self.episode_return = 0.0
        self.completed: list = []

    def sample(self, params, n_steps: int, random_actions: bool = False):
        import jax

        rows = {k: [] for k in
                ("obs", "actions", "rewards", "next_obs", "dones")}
        rng = np.random.default_rng(
            (self.seed, self.runner_idx, self._step_count)
        )
        base_key = jax.random.fold_in(
            jax.random.PRNGKey(self.seed), self.runner_idx
        )
        for _ in range(n_steps):
            if random_actions:
                action = rng.uniform(-1.0, 1.0, self.env.action_size)
            else:
                batch = self.env_to_module({"obs": self.obs})
                key = jax.random.fold_in(base_key, self._step_count)
                out = self.module.forward_exploration(params, batch, key)
                action = np.asarray(out["actions"])[0]
            env_action = self.module_to_env({"actions": action})["actions"]
            next_obs, reward, done, _ = self.env.step(env_action)
            rows["obs"].append(np.asarray(self.obs, np.float32))
            rows["actions"].append(np.asarray(action, np.float32))
            rows["rewards"].append(np.float32(reward))
            rows["next_obs"].append(np.asarray(next_obs, np.float32))
            rows["dones"].append(done)
            self.episode_return += reward
            self._step_count += 1
            if done:
                self.completed.append(self.episode_return)
                self.episode_return = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        episodes, self.completed = self.completed, []
        return (
            {k: np.asarray(v) for k, v in rows.items()},
            episodes,
        )


def make_sac_update(module, tx, alpha_tx, gamma, tau, target_entropy,
                    extra_critic_loss=None):
    """Build the jittable SAC update step shared by SAC and its offline
    extensions (reference: ray CQL extends SAC's learner for exactly this
    reason).  ``extra_critic_loss(params, batch, q1_data, q2_data, key)``
    adds a regularizer to the Bellman loss (CQL's conservative penalty);
    its gradient flows into the critic nets only, like the Bellman term.
    """
    import jax
    import jax.numpy as jnp
    import optax as _optax

    def update(params, target_params, log_alpha, opt_state,
               alpha_opt_state, batch, key):
        alpha = jnp.exp(log_alpha)
        k1, k2, k3 = jax.random.split(key, 3)

        # Critic target: r + gamma * (min target-Q(s', a') - alpha logp')
        next_a, next_logp = module.sample_action(
            target_params, batch["next_obs"], k1
        )
        tq1, tq2 = module.q_values(
            target_params, batch["next_obs"], next_a
        )
        target_v = jnp.minimum(tq1, tq2) - alpha * next_logp
        nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
        target_q = batch["rewards"] + gamma * nonterminal * target_v
        target_q = jax.lax.stop_gradient(target_q)

        def critic_loss(p):
            q1, q2 = module.q_values(p, batch["obs"], batch["actions"])
            bellman = ((q1 - target_q) ** 2 + (q2 - target_q) ** 2).mean()
            extra = (
                extra_critic_loss(p, batch, q1, q2, k3)
                if extra_critic_loss is not None
                else jnp.float32(0.0)
            )
            return bellman + extra, (bellman, extra)

        def actor_loss(p):
            a, logp = module.sample_action(p, batch["obs"], k2)
            q1, q2 = module.q_values(p, batch["obs"], a)
            # Critic params are held fixed for the actor step via the
            # combined-gradient trick below (single optimizer).
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

        (closs, (bellman, extra)), cgrads = jax.value_and_grad(
            critic_loss, has_aux=True
        )(params)
        (aloss, logp), agrads = jax.value_and_grad(
            actor_loss, has_aux=True
        )(params)
        # Actor gradients must not update the critics (and vice versa):
        # zero the cross terms.
        grads = {
            "pi": agrads["pi"],
            "q1": cgrads["q1"],
            "q2": cgrads["q2"],
        }
        updates, opt_state = tx.update(grads, opt_state, params)
        params = _optax.apply_updates(params, updates)

        def alpha_loss(la):
            return (
                -jnp.exp(la)
                * jax.lax.stop_gradient(logp + target_entropy)
            ).mean()

        _al, agrad = jax.value_and_grad(alpha_loss)(log_alpha)
        aupd, alpha_opt_state = alpha_tx.update(
            agrad, alpha_opt_state, log_alpha
        )
        log_alpha = _optax.apply_updates(log_alpha, aupd)

        target_params = jax.tree.map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params
        )
        stats = {
            "critic_loss": closs,
            "bellman_loss": bellman,
            "extra_critic_loss": extra,
            "actor_loss": aloss,
            "alpha": jnp.exp(log_alpha),
        }
        return (params, target_params, log_alpha, opt_state,
                alpha_opt_state, stats)

    return update


class SAC(Algorithm):
    def setup(self, config: SACConfig):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.core.serialization import dumps_function

        hp = self.hp = config.hp
        env_maker = config.env_maker
        if env_maker is None:
            from .env import Pendulum

            env_maker = Pendulum
        probe = env_maker()
        self.obs_size = probe.observation_size
        self.action_size = probe.action_size
        low = getattr(probe, "action_low", -1.0)
        high = getattr(probe, "action_high", 1.0)

        config.rl_module_spec.model_config.setdefault("hidden", hp.hidden)
        self.module = config.rl_module_spec.build(
            self.obs_size, self.action_size
        )
        key = jax.random.PRNGKey(hp.seed)
        self.params = self.module.init_state(key)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.log_alpha = jnp.asarray(np.log(hp.init_alpha), jnp.float32)
        target_entropy = (
            hp.target_entropy
            if hp.target_entropy is not None
            else -float(self.action_size)
        )

        self.tx = optax.adam(hp.lr)
        self.opt_state = self.tx.init(self.params)
        self.alpha_tx = optax.adam(hp.lr)
        self.alpha_opt_state = self.alpha_tx.init(self.log_alpha)
        self.buffer = ReplayBuffer(hp.buffer_capacity, seed=hp.seed)
        module = self.module
        gamma, tau = hp.gamma, hp.tau

        self._update = jax.jit(
            make_sac_update(
                module, self.tx, self.alpha_tx, gamma, tau, target_entropy
            )
        )
        env_payload = dumps_function(env_maker)
        self.runners = [
            _SACRunner.remote(
                env_payload, config.rl_module_spec, hp.seed, i,
                low, high,
            )
            for i in range(max(1, hp.num_env_runners))
        ]
        self._total_steps = 0
        self._episode_returns: list = []

    def training_step(self) -> Dict[str, Any]:
        import jax

        hp = self.hp
        random_phase = self._total_steps < hp.warmup_steps
        refs = [
            r.sample.remote(self.params, hp.rollout_steps, random_phase)
            for r in self.runners
        ]
        for batch, episodes in ray_tpu.get(refs, timeout=600):
            self.buffer.add_batch(batch)
            self._episode_returns.extend(episodes)
            self._total_steps += len(batch["rewards"])
        stats = {}
        if len(self.buffer) >= hp.batch_size and not random_phase:
            key = jax.random.PRNGKey(self._total_steps)
            for i, k in enumerate(jax.random.split(key, hp.learn_steps_per_iter)):
                batch = self.buffer.sample(hp.batch_size)
                batch = {
                    k2: jax.numpy.asarray(v) for k2, v in batch.items()
                }
                (self.params, self.target_params, self.log_alpha,
                 self.opt_state, self.alpha_opt_state, stats) = self._update(
                    self.params, self.target_params, self.log_alpha,
                    self.opt_state, self.alpha_opt_state, batch, k,
                )
        recent = self._episode_returns[-20:]
        return {
            "episode_return_mean": (
                float(np.mean(recent)) if recent else float("nan")
            ),
            "num_env_steps_sampled": self._total_steps,
            **{k: float(v) for k, v in stats.items()},
        }

    def get_state(self):
        return {
            "params": self.params,
            "target_params": self.target_params,
            "log_alpha": self.log_alpha,
            "total_steps": self._total_steps,
        }

    def set_state(self, state):
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.log_alpha = state["log_alpha"]
        self._total_steps = state["total_steps"]

    def cleanup(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass


SACConfig.ALGO_CLS = SAC
