"""DQN — double-DQN with target network and (prioritized) replay.

Reference: ray ``rllib/algorithms/dqn/`` (new-API DQN: EnvRunners with
epsilon-greedy exploration feeding a replay buffer, Learner doing the
double-DQN TD update).  TPU-first: the TD update is one jitted function;
sampling stays on CPU actors with numpy forwards.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.core.serialization import dumps_function

from .actor_manager import FaultTolerantActorManager
from .algorithm import (
    Algorithm,
    AlgorithmConfig,
    init_mlp,
    mlp_forward,
    mlp_forward_np,
)

logger = logging.getLogger(__name__)

_N_LAYERS = 2  # hidden + head


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.rollout_steps = 64
        self.hidden = 64
        self.buffer_capacity = 50_000
        self.learn_batch_size = 64
        self.num_learn_steps = 16  # per train() iteration
        self.target_update_freq = 4  # iterations between target syncs
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_iters = 30
        self.min_buffer_size = 256
        self.prioritized = False
        self.double_q = True


@ray_tpu.remote
class DQNEnvRunner:
    """Epsilon-greedy sampler returning transition tuples."""

    def __init__(self, env_maker_payload: bytes, seed: int):
        from ray_tpu.core.serialization import loads_function

        self.env = loads_function(env_maker_payload)()
        self.rng = np.random.default_rng(seed)
        self.obs = self.env.reset()
        self.episode_return = 0.0
        self.completed: List[float] = []

    def sample(self, params: Dict[str, np.ndarray], num_steps: int,
               epsilon: float):
        obs_b, act_b, rew_b, next_b, done_b = [], [], [], [], []
        for _ in range(num_steps):
            if self.rng.random() < epsilon:
                action = int(self.rng.integers(self.env.num_actions))
            else:
                q = mlp_forward_np(params, self.obs, _N_LAYERS)
                action = int(np.argmax(q))
            next_obs, reward, done, _ = self.env.step(action)
            obs_b.append(self.obs)
            act_b.append(action)
            rew_b.append(reward)
            next_b.append(next_obs)
            done_b.append(done)
            self.episode_return += reward
            self.obs = next_obs
            if done:
                self.completed.append(self.episode_return)
                self.episode_return = 0.0
                self.obs = self.env.reset()
        returns, self.completed = self.completed, []
        return {
            "obs": np.asarray(obs_b, np.float32),
            "actions": np.asarray(act_b, np.int64),
            "rewards": np.asarray(rew_b, np.float32),
            "next_obs": np.asarray(next_b, np.float32),
            "dones": np.asarray(done_b, np.float32),
        }, returns


class DQN(Algorithm):
    def setup(self, config: DQNConfig) -> None:
        import jax
        import optax

        from .env import CartPole
        from .replay import PrioritizedReplayBuffer, ReplayBuffer

        maker = config.env_maker or (lambda: CartPole())
        self._maker_payload = dumps_function(maker)
        probe = maker()
        obs_size, num_actions = probe.observation_size, probe.num_actions

        key = jax.random.PRNGKey(config.seed)
        sizes = [obs_size, config.hidden, num_actions]
        self.params = init_mlp(key, sizes)
        self.target_params = jax.tree.map(np.copy, self.params)
        self.tx = optax.adam(config.lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = (
            PrioritizedReplayBuffer(config.buffer_capacity, seed=config.seed)
            if config.prioritized
            else ReplayBuffer(config.buffer_capacity, seed=config.seed)
        )

        gamma, double_q = config.gamma, config.double_q
        tx = self.tx

        def td_update(params, target_params, opt_state, batch, weights):
            import jax.numpy as jnp

            def loss_fn(p):
                q = mlp_forward(p, batch["obs"], _N_LAYERS)
                q_sa = jnp.take_along_axis(
                    q, batch["actions"][:, None], axis=1
                )[:, 0]
                q_next_target = mlp_forward(
                    target_params, batch["next_obs"], _N_LAYERS
                )
                if double_q:
                    q_next_online = mlp_forward(
                        p, batch["next_obs"], _N_LAYERS
                    )
                    next_a = jnp.argmax(q_next_online, axis=1)
                else:
                    next_a = jnp.argmax(q_next_target, axis=1)
                next_q = jnp.take_along_axis(
                    q_next_target, next_a[:, None], axis=1
                )[:, 0]
                target = batch["rewards"] + gamma * (1 - batch["dones"]) * (
                    jax.lax.stop_gradient(next_q)
                )
                td = q_sa - target
                loss = jnp.mean(weights * td**2)
                return loss, td

            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._td_update = jax.jit(td_update)
        self.runner_group = FaultTolerantActorManager(
            lambda i: DQNEnvRunner.remote(
                self._maker_payload, config.seed + i
            ),
            config.num_env_runners,
        )

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        np_params = {k: np.asarray(v) for k, v in self.params.items()}
        eps = self._epsilon()
        results = self.runner_group.foreach(
            "sample", np_params, cfg.rollout_steps, eps
        )
        episode_returns: List[float] = []
        steps = 0
        for _, (batch, returns) in results:
            self.buffer.add_batch(batch)
            episode_returns.extend(returns)
            steps += len(batch["obs"])

        loss = None
        if len(self.buffer) >= cfg.min_buffer_size:
            for _ in range(cfg.num_learn_steps):
                sample = self.buffer.sample(cfg.learn_batch_size)
                weights = sample.pop("_weights", None)
                indices = sample.pop("_indices", None)
                w = (
                    jnp.asarray(weights)
                    if weights is not None
                    else jnp.ones(cfg.learn_batch_size, np.float32)
                )
                jb = {k: jnp.asarray(v) for k, v in sample.items()}
                self.params, self.opt_state, loss, td = self._td_update(
                    self.params, self.target_params, self.opt_state, jb, w
                )
                if indices is not None:
                    self.buffer.update_priorities(indices, np.asarray(td))
        if self.iteration % cfg.target_update_freq == 0:
            import jax

            self.target_params = jax.tree.map(np.copy, self.params)
        return {
            "episode_return_mean": (
                float(np.mean(episode_returns)) if episode_returns else None
            ),
            "num_env_steps_sampled": steps,
            "epsilon": eps,
            "buffer_size": len(self.buffer),
            "loss": float(loss) if loss is not None else None,
        }

    def get_state(self) -> Dict[str, Any]:
        return {
            "params": {k: np.asarray(v) for k, v in self.params.items()},
            "target_params": {
                k: np.asarray(v) for k, v in self.target_params.items()
            },
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = self.tx.init(self.params)

    def cleanup(self) -> None:
        self.runner_group.kill_all()


DQNConfig.ALGO_CLS = DQN
