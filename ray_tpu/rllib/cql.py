"""Conservative Q-Learning — offline RL on the SAC module.

Reference: ray ``rllib/algorithms/cql/cql.py`` (+ ``cql_torch_learner``):
SAC's actor/critic/alpha losses plus the CQL(H) conservative regularizer
on both critics — logsumexp of Q over sampled (uniform + policy) actions
minus Q on dataset actions — which pushes Q down on out-of-distribution
actions so the learned policy stays inside the dataset's support.  Purely
offline: no env runners; transitions stream from ``OfflineData``.

Actions are stored NORMALIZED to the module's [-1, 1] tanh range; callers
scale to env units at evaluation time (``ScaleActions``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .offline import OfflineData
from .rl_module import RLModuleSpec, SACModule
from .sac import make_sac_update


@dataclasses.dataclass
class CQLHyperparams:
    lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005
    hidden: int = 64
    batch_size: int = 256
    learn_steps_per_iter: int = 200
    init_alpha: float = 0.2
    target_entropy: Optional[float] = None
    # CQL(H) regularizer
    cql_alpha: float = 1.0
    cql_n_actions: int = 8
    seed: int = 0


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.hp = CQLHyperparams()
        self.offline_data = None
        self.env_maker: Optional[Callable] = None  # evaluation only
        self.rl_module_spec = RLModuleSpec(SACModule, {})

    def training(self, **kwargs) -> "CQLConfig":
        for k, v in kwargs.items():
            if not hasattr(self.hp, k):
                raise ValueError(f"unknown CQL hyperparam {k!r}")
            setattr(self.hp, k, v)
        return self

    def offline(self, data) -> "CQLConfig":
        self.offline_data = data
        return self

    def environment(self, env_maker) -> "CQLConfig":
        self.env_maker = env_maker
        return self

    def rl_module(self, spec: RLModuleSpec) -> "CQLConfig":
        self.rl_module_spec = spec
        return self


class CQL(Algorithm):
    def setup(self, config: CQLConfig) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        hp = self.hp = config.hp
        if config.offline_data is None:
            raise ValueError("CQL requires .offline(data)")
        self.data = (
            config.offline_data
            if isinstance(config.offline_data, OfflineData)
            else OfflineData(config.offline_data, seed=hp.seed)
        )
        self.env_maker = config.env_maker
        probe_batch = self.data.sample(2)
        obs_size = probe_batch["obs"].shape[1]
        action_size = probe_batch["actions"].shape[1]
        self.obs_size, self.action_size = obs_size, action_size

        config.rl_module_spec.model_config.setdefault("hidden", hp.hidden)
        self.module = module = config.rl_module_spec.build(
            obs_size, action_size
        )
        key = jax.random.PRNGKey(hp.seed)
        self.params = module.init_state(key)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.log_alpha = jnp.asarray(np.log(hp.init_alpha), jnp.float32)
        target_entropy = (
            hp.target_entropy
            if hp.target_entropy is not None
            else -float(action_size)
        )
        self.tx = optax.adam(hp.lr)
        self.opt_state = self.tx.init(self.params)
        self.alpha_tx = optax.adam(hp.lr)
        self.alpha_opt_state = self.alpha_tx.init(self.log_alpha)

        gamma, tau = hp.gamma, hp.tau
        cql_alpha, n_rand = hp.cql_alpha, hp.cql_n_actions

        def cql_penalty(p, obs, data_q1, data_q2, key):
            """logsumexp over {uniform, current-policy} actions minus the
            dataset-action Q — per critic (the CQL(H) estimator)."""
            b = obs.shape[0]
            krand, kpi = jax.random.split(key)
            rand_a = jax.random.uniform(
                krand, (n_rand, b, action_size), minval=-1.0, maxval=1.0
            )
            pi_a, pi_logp = module.sample_action(p, obs, kpi)

            def q_of(a):
                return module.q_values(p, obs, a)

            q1s, q2s = jax.vmap(q_of)(rand_a)  # [n_rand, B]
            pq1, pq2 = module.q_values(p, obs, pi_a)
            # Importance correction: uniform proposals have log-density
            # -A*log(2); policy proposals use their own logp.
            log_u = -action_size * jnp.log(2.0)
            cat1 = jnp.concatenate(
                [q1s - log_u, (pq1 - pi_logp)[None]], axis=0
            )
            cat2 = jnp.concatenate(
                [q2s - log_u, (pq2 - pi_logp)[None]], axis=0
            )
            ls1 = jax.scipy.special.logsumexp(cat1, axis=0)
            ls2 = jax.scipy.special.logsumexp(cat2, axis=0)
            return (ls1 - data_q1).mean() + (ls2 - data_q2).mean()

        def conservative_extra(p, batch, q1_data, q2_data, key):
            return cql_alpha * cql_penalty(p, batch["obs"], q1_data,
                                           q2_data, key)

        update = make_sac_update(
            module, self.tx, self.alpha_tx, gamma, tau, target_entropy,
            extra_critic_loss=conservative_extra,
        )

        # Many updates per jit call: stack K sampled batches and lax.scan
        # the update over them — the dominant cost at this model size is
        # per-call dispatch, not FLOPs.
        def update_many(params, target_params, log_alpha, opt_state,
                       alpha_opt_state, batches, base_key):
            def body(carry, xs):
                batch, key = xs
                out = update(*carry, batch, key)
                return out[:-1], out[-1]

            n = batches["rewards"].shape[0]
            keys = jax.random.split(base_key, n)
            (params, target_params, log_alpha, opt_state,
             alpha_opt_state), stats = jax.lax.scan(
                body,
                (params, target_params, log_alpha, opt_state,
                 alpha_opt_state),
                (batches, keys),
            )
            last = jax.tree.map(lambda s: s[-1], stats)
            return (params, target_params, log_alpha, opt_state,
                    alpha_opt_state, last)

        self._update_many = jax.jit(update_many)
        self._steps = 0

    _SCAN_CHUNK = 50

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        hp = self.hp
        stats = {}
        remaining = hp.learn_steps_per_iter
        while remaining > 0:
            k = min(self._SCAN_CHUNK, remaining)
            remaining -= k
            sampled = [self.data.sample(hp.batch_size) for _ in range(k)]
            batches = {
                "obs": jnp.asarray(
                    np.stack([b["obs"] for b in sampled]), jnp.float32
                ),
                "actions": jnp.asarray(
                    np.stack([b["actions"] for b in sampled]), jnp.float32
                ),
                "rewards": jnp.asarray(
                    np.stack([b["rewards"] for b in sampled]), jnp.float32
                ),
                "next_obs": jnp.asarray(
                    np.stack([b["next_obs"] for b in sampled]), jnp.float32
                ),
                "dones": jnp.asarray(np.stack([b["dones"] for b in sampled])),
            }
            self._steps += k
            key = jax.random.fold_in(
                jax.random.PRNGKey(hp.seed), self._steps
            )
            (self.params, self.target_params, self.log_alpha,
             self.opt_state, self.alpha_opt_state, stats) = self._update_many(
                self.params, self.target_params, self.log_alpha,
                self.opt_state, self.alpha_opt_state, batches, key,
            )
        out = {k: float(v) for k, v in stats.items()}
        if "extra_critic_loss" in out:
            out["cql_penalty"] = out.pop("extra_critic_loss")
        out["learn_steps_total"] = self._steps
        return out

    # ------------------------------------------------------------ evaluation
    def evaluate(self, episodes: int = 5, seed: int = 100) -> Dict[str, Any]:
        """Greedy rollout of the learned policy in the (eval-only) env."""
        if self.env_maker is None:
            raise ValueError("evaluate() requires .environment(env_maker)")
        import jax.numpy as jnp

        returns = []
        for ep in range(episodes):
            env = self.env_maker(seed=seed + ep) if _takes_seed(
                self.env_maker
            ) else self.env_maker()
            lo = getattr(env, "action_low", -1.0)
            hi = getattr(env, "action_high", 1.0)
            obs = env.reset()
            total, done = 0.0, False
            while not done:
                out = self.module.forward_inference(
                    self.params, {"obs": jnp.asarray(obs, jnp.float32)[None]}
                )
                a = np.asarray(out["actions"])[0]
                env_a = lo + (a + 1.0) * 0.5 * (hi - lo)
                obs, r, done, _ = env.step(env_a)
                total += r
            returns.append(total)
        return {
            "episode_return_mean": float(np.mean(returns)),
            "episodes": episodes,
        }

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {
            "params": jax.tree.map(np.asarray, self.params),
            "target_params": jax.tree.map(np.asarray, self.target_params),
            "log_alpha": np.asarray(self.log_alpha),
            "steps": self._steps,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        import jax.numpy as jnp

        self.log_alpha = jnp.asarray(state["log_alpha"])
        self.opt_state = self.tx.init(self.params)
        self.alpha_opt_state = self.alpha_tx.init(self.log_alpha)
        self._steps = state.get("steps", 0)


def _takes_seed(env_maker) -> bool:
    import inspect

    try:
        return "seed" in inspect.signature(env_maker).parameters
    except (TypeError, ValueError):
        return False


CQLConfig.ALGO_CLS = CQL
