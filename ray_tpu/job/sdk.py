"""Job submission SDK.

Reference: ``JobSubmissionClient`` (ray ``dashboard/modules/job/sdk.py:36``,
``submit_job:126``) + ``JobManager``/``JobSupervisor`` (ray
``dashboard/modules/job/job_manager.py:61``).  Architecture kept: one
detached supervisor actor per job owns the entrypoint subprocess; job
metadata lives in the control-plane KV so any client can list jobs.  The
supervisor is placed like any actor (it requests no resources), and the
entrypoint subprocess inherits the cluster address so its own
``ray_tpu.init(address=…)`` joins the same cluster.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_KV_NS = "_job_submissions"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Dict[str, str] = field(default_factory=dict)
    driver_exit_code: Optional[int] = None


class JobSupervisor:
    """Detached actor owning one job's entrypoint subprocess (ray
    ``dashboard/modules/job/job_manager.py`` JobSupervisor analog)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 metadata: Optional[Dict[str, str]] = None,
                 env_vars: Optional[Dict[str, str]] = None):
        self._info = JobInfo(
            submission_id=submission_id,
            entrypoint=entrypoint,
            metadata=metadata or {},
        )
        self._env_vars = env_vars or {}
        self._proc: Optional[subprocess.Popen] = None
        self._log_path = os.path.join(
            os.environ.get("RAY_TPU_LOG_DIR", "/tmp/ray_tpu"),
            f"job-{submission_id}.log",
        )
        self._lock = threading.Lock()
        self._publish()

    def _publish(self):
        import ray_tpu

        worker = ray_tpu.api.global_worker()
        worker.kv_put(_KV_NS, self._info.submission_id, self._info.__dict__)

    def run(self) -> str:
        """Start the entrypoint subprocess and reap it in the background."""
        env = dict(os.environ)
        env.update(self._env_vars)
        env["RAY_TPU_JOB_SUBMISSION_ID"] = self._info.submission_id
        with self._lock:
            if self._proc is not None:
                return self._info.status
            out = open(self._log_path, "ab")
            try:
                self._proc = subprocess.Popen(
                    self._info.entrypoint,
                    shell=True,
                    stdout=out,
                    stderr=subprocess.STDOUT,
                    env=env,
                    start_new_session=True,
                )
            except OSError as e:
                self._info.status = JobStatus.FAILED
                self._info.message = f"failed to start entrypoint: {e}"
                self._publish()
                return self._info.status
            self._info.status = JobStatus.RUNNING
            self._info.start_time = time.time()
            self._publish()
        threading.Thread(target=self._reap, daemon=True).start()
        return self._info.status

    def _reap(self):
        code = self._proc.wait()
        with self._lock:
            if self._info.status == JobStatus.RUNNING:
                self._info.status = (
                    JobStatus.SUCCEEDED if code == 0 else JobStatus.FAILED
                )
                self._info.message = f"entrypoint exited with code {code}"
            self._info.driver_exit_code = code
            self._info.end_time = time.time()
            self._publish()

    def status(self) -> dict:
        return dict(self._info.__dict__)

    def logs(self, tail_bytes: int = 1 << 20) -> str:
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop(self) -> bool:
        with self._lock:
            if self._proc is None or self._proc.poll() is not None:
                return False
            self._info.status = JobStatus.STOPPED
            self._info.message = "stopped by user"
        try:
            os.killpg(os.getpgid(self._proc.pid), 15)
        except OSError:
            pass

        def force_kill():
            time.sleep(3)
            if self._proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self._proc.pid), 9)
                except OSError:
                    pass

        threading.Thread(target=force_kill, daemon=True).start()
        self._publish()
        return True


def _supervisor_name(submission_id: str) -> str:
    return f"_rtpu_job:{submission_id}"


class JobSubmissionClient:
    """Submit and manage jobs on a running cluster (ray
    ``dashboard/modules/job/sdk.py:36`` analog; transport is the cluster's
    own actor RPC instead of the dashboard's REST endpoint)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address or "auto")
        self._ray = ray_tpu

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        from ..core.runtime_env import resolve_runtime_env

        submission_id = submission_id or f"rtpu-job-{uuid.uuid4().hex[:10]}"
        if self.get_job_info(submission_id) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        env_vars = resolve_runtime_env(runtime_env) or {}
        supervisor_cls = self._ray.remote(num_cpus=0)(JobSupervisor)
        supervisor = supervisor_cls.options(
            name=_supervisor_name(submission_id),
            lifetime="detached",
        ).remote(submission_id, entrypoint, metadata, env_vars)
        # Synchronous start so submit errors surface here.
        self._ray.get(supervisor.run.remote(), timeout=60)
        return submission_id

    def _supervisor(self, submission_id: str):
        try:
            return self._ray.get_actor(_supervisor_name(submission_id))
        except ValueError:
            return None

    def get_job_info(self, submission_id: str) -> Optional[JobInfo]:
        worker = self._ray.api.global_worker()
        raw = worker.kv_get(_KV_NS, submission_id)
        if raw is None:
            return None
        return JobInfo(**raw)

    def get_job_status(self, submission_id: str) -> Optional[str]:
        info = self.get_job_info(submission_id)
        return info.status if info else None

    def get_job_logs(self, submission_id: str) -> str:
        sup = self._supervisor(submission_id)
        if sup is None:
            return ""
        return self._ray.get(sup.logs.remote(), timeout=30)

    def stop_job(self, submission_id: str) -> bool:
        sup = self._supervisor(submission_id)
        if sup is None:
            return False
        return self._ray.get(sup.stop.remote(), timeout=30)

    def delete_job(self, submission_id: str) -> bool:
        info = self.get_job_info(submission_id)
        if info is None:
            return False
        if info.status not in JobStatus.TERMINAL:
            raise RuntimeError(
                f"job {submission_id!r} is {info.status}; stop it first"
            )
        sup = self._supervisor(submission_id)
        if sup is not None:
            self._ray.kill(sup)
        worker = self._ray.api.global_worker()
        worker.kv_del(_KV_NS, submission_id)
        return True

    def list_jobs(self) -> List[JobInfo]:
        worker = self._ray.api.global_worker()
        out = []
        for key in worker.kv_keys(_KV_NS):
            raw = worker.kv_get(_KV_NS, key)
            if raw is not None:
                out.append(JobInfo(**raw))
        return out

    def wait_until_finished(
        self, submission_id: str, timeout: float = 300, poll_s: float = 0.5
    ) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {submission_id} still running after {timeout}s")
