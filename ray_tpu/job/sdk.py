"""Job submission SDK.

Reference: ``JobSubmissionClient`` (ray ``dashboard/modules/job/sdk.py:36``,
``submit_job:126``) + ``JobManager``/``JobSupervisor`` (ray
``dashboard/modules/job/job_manager.py:61``).  Architecture kept: one
detached supervisor actor per job owns the entrypoint subprocess; job
metadata lives in the control-plane KV so any client can list jobs.  The
supervisor is placed like any actor (it requests no resources), and the
entrypoint subprocess inherits the cluster address so its own
``ray_tpu.init(address=…)`` joins the same cluster.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_KV_NS = "_job_submissions"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Dict[str, str] = field(default_factory=dict)
    driver_exit_code: Optional[int] = None


class JobSupervisor:
    """Detached actor owning one job's entrypoint subprocess (ray
    ``dashboard/modules/job/job_manager.py`` JobSupervisor analog)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 metadata: Optional[Dict[str, str]] = None,
                 env_vars: Optional[Dict[str, str]] = None):
        self._info = JobInfo(
            submission_id=submission_id,
            entrypoint=entrypoint,
            metadata=metadata or {},
        )
        self._env_vars = env_vars or {}
        self._proc: Optional[subprocess.Popen] = None
        self._log_path = os.path.join(
            os.environ.get("RAY_TPU_LOG_DIR", "/tmp/ray_tpu"),
            f"job-{submission_id}.log",
        )
        self._lock = threading.Lock()
        self._publish()

    def _publish(self):
        import ray_tpu

        worker = ray_tpu.api.global_worker()
        worker.kv_put(_KV_NS, self._info.submission_id, self._info.__dict__)

    def run(self) -> str:
        """Start the entrypoint subprocess and reap it in the background."""
        env = dict(os.environ)
        env.update(self._env_vars)
        env["RAY_TPU_JOB_SUBMISSION_ID"] = self._info.submission_id
        with self._lock:
            if self._proc is not None:
                return self._info.status
            out = open(self._log_path, "ab")
            try:
                self._proc = subprocess.Popen(
                    self._info.entrypoint,
                    shell=True,
                    stdout=out,
                    stderr=subprocess.STDOUT,
                    env=env,
                    start_new_session=True,
                )
            except OSError as e:
                self._info.status = JobStatus.FAILED
                self._info.message = f"failed to start entrypoint: {e}"
                self._publish()
                return self._info.status
            self._info.status = JobStatus.RUNNING
            self._info.start_time = time.time()
            self._publish()
        threading.Thread(target=self._reap, daemon=True,
                         name="job-reaper").start()
        return self._info.status

    def _reap(self):
        code = self._proc.wait()
        with self._lock:
            if self._info.status == JobStatus.RUNNING:
                self._info.status = (
                    JobStatus.SUCCEEDED if code == 0 else JobStatus.FAILED
                )
                self._info.message = f"entrypoint exited with code {code}"
            self._info.driver_exit_code = code
            self._info.end_time = time.time()
            self._publish()

    def status(self) -> dict:
        return dict(self._info.__dict__)

    def logs(self, tail_bytes: int = 1 << 20) -> str:
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop(self) -> bool:
        with self._lock:
            if self._proc is None or self._proc.poll() is not None:
                return False
            self._info.status = JobStatus.STOPPED
            self._info.message = "stopped by user"
        try:
            os.killpg(os.getpgid(self._proc.pid), 15)
        except OSError:
            pass

        def force_kill():
            time.sleep(3)
            if self._proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self._proc.pid), 9)
                except OSError:
                    pass

        threading.Thread(target=force_kill, daemon=True,
                         name="job-force-kill").start()
        self._publish()
        return True


def _supervisor_name(submission_id: str) -> str:
    return f"_rtpu_job:{submission_id}"


class JobSubmissionClient:
    """Submit and manage jobs on a running cluster (ray
    ``dashboard/modules/job/sdk.py:36`` analog).

    Two transports, chosen by the address scheme:

    - ``http://host:port`` — REST against the dashboard's ``/api/jobs``
      endpoints, from OUTSIDE the cluster (no ray_tpu.init), exactly like
      the reference client.
    - anything else (or None) — the cluster's own actor RPC from a
      connected driver.
    """

    def __new__(cls, address: Optional[str] = None):
        if address and address.startswith(("http://", "https://")):
            return object.__new__(_HttpJobSubmissionClient)
        return object.__new__(cls)

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address or "auto")
        self._ray = ray_tpu

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        from ..core.runtime_env import resolve_runtime_env

        submission_id = submission_id or f"rtpu-job-{uuid.uuid4().hex[:10]}"
        if self.get_job_info(submission_id) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        env_vars = resolve_runtime_env(runtime_env) or {}
        supervisor_cls = self._ray.remote(num_cpus=0)(JobSupervisor)
        supervisor = supervisor_cls.options(
            name=_supervisor_name(submission_id),
            lifetime="detached",
        ).remote(submission_id, entrypoint, metadata, env_vars)
        # Synchronous start so submit errors surface here.
        self._ray.get(supervisor.run.remote(), timeout=60)
        return submission_id

    def _supervisor(self, submission_id: str):
        try:
            return self._ray.get_actor(_supervisor_name(submission_id))
        except ValueError:
            return None

    def get_job_info(self, submission_id: str) -> Optional[JobInfo]:
        worker = self._ray.api.global_worker()
        raw = worker.kv_get(_KV_NS, submission_id)
        if raw is None:
            return None
        return JobInfo(**raw)

    def get_job_status(self, submission_id: str) -> Optional[str]:
        info = self.get_job_info(submission_id)
        return info.status if info else None

    def get_job_logs(self, submission_id: str) -> str:
        sup = self._supervisor(submission_id)
        if sup is None:
            return ""
        return self._ray.get(sup.logs.remote(), timeout=30)

    def stop_job(self, submission_id: str) -> bool:
        sup = self._supervisor(submission_id)
        if sup is None:
            return False
        return self._ray.get(sup.stop.remote(), timeout=30)

    def delete_job(self, submission_id: str) -> bool:
        info = self.get_job_info(submission_id)
        if info is None:
            return False
        if info.status not in JobStatus.TERMINAL:
            raise RuntimeError(
                f"job {submission_id!r} is {info.status}; stop it first"
            )
        sup = self._supervisor(submission_id)
        if sup is not None:
            self._ray.kill(sup)
        worker = self._ray.api.global_worker()
        worker.kv_del(_KV_NS, submission_id)
        return True

    def list_jobs(self) -> List[JobInfo]:
        worker = self._ray.api.global_worker()
        out = []
        for key in worker.kv_keys(_KV_NS):
            raw = worker.kv_get(_KV_NS, key)
            if raw is not None:
                out.append(JobInfo(**raw))
        return out

    def wait_until_finished(
        self, submission_id: str, timeout: float = 300, poll_s: float = 0.5
    ) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {submission_id} still running after {timeout}s")


class _HttpJobSubmissionClient(JobSubmissionClient):
    """REST transport: talks to the dashboard's /api/jobs endpoints with
    stdlib urllib — usable from a machine that is NOT part of the cluster
    (reference: dashboard/modules/job/sdk.py:36)."""

    def __init__(self, address: str):
        self._base = address.rstrip("/")

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        import json as _json
        import urllib.error
        import urllib.request

        data = _json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._base + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return _json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            payload = e.read().decode()
            try:
                msg = _json.loads(payload).get("error", payload)
            except ValueError:
                msg = payload
            if e.code == 404:
                return None
            if e.code == 409:
                raise ValueError(msg) from None
            raise RuntimeError(f"HTTP {e.code}: {msg}") from None

    def submit_job(self, *, entrypoint: str, submission_id=None,
                   runtime_env=None, metadata=None) -> str:
        reply = self._request("POST", "/api/jobs", {
            "entrypoint": entrypoint,
            "submission_id": submission_id,
            "runtime_env": runtime_env,
            "metadata": metadata,
        })
        return reply["submission_id"]

    def get_job_info(self, submission_id: str) -> Optional[JobInfo]:
        reply = self._request("GET", f"/api/jobs/{submission_id}")
        if reply is None:
            return None
        return JobInfo(**{k: reply[k] for k in JobInfo.__dataclass_fields__})

    def get_job_logs(self, submission_id: str) -> str:
        reply = self._request("GET", f"/api/jobs/{submission_id}/logs")
        return "" if reply is None else reply.get("logs", "")

    def stop_job(self, submission_id: str) -> bool:
        reply = self._request("POST", f"/api/jobs/{submission_id}/stop")
        return bool(reply and reply.get("stopped"))

    def delete_job(self, submission_id: str) -> bool:
        reply = self._request("DELETE", f"/api/jobs/{submission_id}")
        return bool(reply and reply.get("deleted"))

    def list_jobs(self) -> List[JobInfo]:
        reply = self._request("GET", "/api/jobs") or []
        out = []
        for rec in reply:
            try:
                out.append(JobInfo(**{
                    k: rec[k] for k in JobInfo.__dataclass_fields__
                }))
            except (KeyError, TypeError):
                continue
        return out
