"""``ray_tpu.job`` — job submission.

Role-equivalent of the reference's job-submission subsystem (ray
``python/ray/dashboard/modules/job/``): a ``JobSubmissionClient`` submits an
entrypoint shell command; a detached ``JobSupervisor`` actor runs it as a
subprocess, tracks status, captures logs, and can stop it.
"""

from .sdk import JobInfo, JobStatus, JobSubmissionClient  # noqa: F401
