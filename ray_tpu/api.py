"""Top-level public API: init/shutdown/get/put/wait/remote/kill.

Equivalent of ray ``python/ray/_private/worker.py`` public functions
(``ray.init:1406``, ``ray.get:2819``, ``ray.put:3002``, ``ray.wait:3073``,
``ray.kill:3253``, ``ray.get_actor:3218``).
"""

from __future__ import annotations

import atexit
from typing import Any, Dict, List, Optional, Sequence, Union

from .core import node as node_mod
from .core.api_frontend import ActorClass, ActorHandle, RemoteFunction, remote  # noqa: F401
from .core.config import GlobalConfig
from .core.core_worker import (
    CoreWorker,
    ObjectRefGenerator,
    global_worker,
    set_global_worker,
    try_global_worker,
)
from .core.exceptions import *  # noqa: F401,F403
from .core.ids import JobID, NodeID
from .core.placement import (  # noqa: F401
    PlacementGroup,
    SlicePlacementGroup,
    placement_group,
    placement_group_strategy,
    remove_placement_group,
)
from .core.task_spec import ObjectRef  # noqa: F401

_local_node: Optional[node_mod.Node] = None
_config_overrides_before: Optional[Dict[str, Any]] = None


def is_initialized() -> bool:
    return try_global_worker() is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    job_priority: Optional[int] = None,
    job_quota: Optional[Dict[str, float]] = None,
    _system_config: Optional[Dict[str, Any]] = None,
) -> "ClientContext":
    """Start a local cluster (head) or connect to an existing one.

    ``address``: None → start head locally; "auto" → discover local head;
    "host:port" → connect to that control plane (starts a local node agent
    for this machine if none is known).

    ``job_priority``/``job_quota``: multi-tenant arbitration inputs for
    this driver's job — higher priority may checkpoint-then-evict
    lower-priority placement groups when chips are contended; quota caps
    the job's durable reservations per resource (over-quota requests
    queue instead of failing).  See ``docs/scheduling.md``.

    .. note:: ``init()`` calls ``gc.collect()`` + ``gc.freeze()`` (a ~3x
       win on sequential call throughput — see the comment at the call
       site).  The freeze covers EVERY object alive at that moment,
       including application objects created before ``init()``: any
       cyclic garbage among them becomes uncollectable until
       ``shutdown()`` un-freezes it (plain refcounted objects are
       unaffected).  Long-lived drivers should therefore ``init()``
       early, before building large temporary object graphs.
    """
    global _local_node, _config_overrides_before
    if is_initialized():
        return ClientContext(global_worker())
    if _system_config:
        # _system_config is cluster-scoped (reference semantics): snapshot
        # the prior overrides so shutdown() restores them — a test process
        # init/shutdown cycle must not leak config into the next cluster.
        _config_overrides_before = dict(GlobalConfig._overrides)
        GlobalConfig.override(**_system_config)

    if address in (None, "local"):
        node = node_mod.Node(
            head=True, resources=resources, labels=labels, num_cpus=num_cpus,
            die_with_parent=True,
        )
        node.start()
        _local_node = node
        cp_address = node.cp_address
        agent_address = node.agent_address
        session_id = node.session_id
    else:
        if address == "auto":
            info = node_mod.read_head_info()
            if info is None:
                raise ConnectionError("no local head found (address='auto')")
            cp_address = info["cp_address"]
            session_id = info["session_id"]
        else:
            cp_address = address
            info = node_mod.read_head_info()
            session_id = info["session_id"] if info else "remote"
        ha_dir = info.get("ha_dir") if info else None
        node = node_mod.Node(
            head=False,
            cp_address=cp_address,
            resources=resources,
            labels=labels,
            session_id=session_id,
            num_cpus=num_cpus,
            ha_dir=ha_dir,
            # A connecting driver's local agent must die with the driver:
            # client processes exiting uncleanly were orphaning 0-CPU
            # agents on shared clusters.
            die_with_parent=True,
        )
        node.start()
        _local_node = node
        agent_address = node.agent_address

    worker = CoreWorker(
        CoreWorker.DRIVER,
        cp_address,
        agent_address,
        session_id,
        NodeID.from_random(),
        job_id=JobID.from_random(),
        job_priority=job_priority,
        job_quota=job_quota,
    )
    worker.start_threaded()
    set_global_worker(worker)
    atexit.register(shutdown)
    # Exclude the just-built permanent heap (imported modules, framework
    # state) from future GC traversals: the per-call garbage of a hot
    # submit/get loop triggers collections whose cost is dominated by
    # walking these long-lived objects — freezing them measured ~3x on
    # sequential actor-call throughput on a 1-core box.  (The classic
    # post-fork/post-init gc.freeze pattern; the reference leaves GC
    # untuned but its per-call path is C++, not collectable objects.)
    import gc

    gc.collect()
    gc.freeze()
    return ClientContext(worker)


def shutdown():
    global _local_node, _config_overrides_before
    worker = try_global_worker()
    if worker is not None:
        worker.shutdown()
        set_global_worker(None)
        # Undo init()'s gc.freeze: without this, every init/shutdown
        # cycle would strand the dead session's object graph (CoreWorker,
        # tasks, tracebacks — cycle-rich) in the permanent generation,
        # growing memory monotonically in long-lived drivers (pytest,
        # notebooks).  Unfreeze returns it to gen2 for normal collection;
        # the next init re-freezes whatever is genuinely permanent.
        import gc

        gc.unfreeze()
    if _local_node is not None:
        _local_node.stop()
        _local_node = None
    if _config_overrides_before is not None:
        # Restoring _overrides alone is not enough: override() also wrote
        # the values into the knob CACHE (__dict__), which would leak the
        # dead cluster's _system_config into the next init in this
        # process (observed: chaos knobs poisoning the next test).
        restored = _config_overrides_before
        _config_overrides_before = None
        GlobalConfig._overrides = {}
        GlobalConfig.reload()
        if restored:
            GlobalConfig.override(**restored)


class ClientContext:
    def __init__(self, worker: CoreWorker):
        self.worker = worker

    @property
    def address_info(self) -> dict:
        return {
            "cp_address": self.worker.cp_address,
            "agent_address": self.worker.agent_address,
            "session_id": self.worker.session_id,
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        shutdown()


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
):
    return global_worker().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return global_worker().put(value)


def wait(
    refs: List[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return global_worker().wait(refs, num_returns=num_returns, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    global_worker().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(refs: Union[ObjectRef, Sequence[ObjectRef]]):
    """Best-effort cancel of the task(s) producing the given ref(s).

    A task still queued (owner-side lease queue or executor-side pipeline
    wait) is skipped and its return refs resolve to ``TaskCancelledError``;
    a task already executing runs to completion and resolves normally; a
    ref from ``put`` or an actor call is ignored.  Returns immediately —
    observe the outcome by getting the refs.
    """
    if isinstance(refs, ObjectRef):
        refs = [refs]
    global_worker().cancel_tasks(list(refs))


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    info = global_worker().get_actor_by_name(name, namespace)
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"actor {name!r} not found in namespace {namespace!r}")
    return ActorHandle(info["actor_id"])


def cluster_resources() -> Dict[str, float]:
    worker = global_worker()
    view = worker._run_sync(worker.cp.call("get_cluster_view"))
    total: Dict[str, float] = {}
    for info in view["nodes"].values():
        for k, v in info["snapshot"]["total"].items():
            total[k] = total.get(k, 0) + v
    return total


def available_resources() -> Dict[str, float]:
    worker = global_worker()
    view = worker._run_sync(worker.cp.call("get_cluster_view"))
    total: Dict[str, float] = {}
    for info in view["nodes"].values():
        for k, v in info["snapshot"]["available"].items():
            total[k] = total.get(k, 0) + v
    return total


def nodes() -> List[dict]:
    worker = global_worker()
    view = worker._run_sync(worker.cp.call("get_cluster_view"))
    return [
        {"node_id": nid.hex(), **info} for nid, info in view["nodes"].items()
    ]


def state_summary() -> dict:
    """Cluster state snapshot (ray.util.state analog)."""
    worker = global_worker()
    return worker._run_sync(worker.cp.call("get_state"))


def timeline_stats() -> dict:
    worker = global_worker()
    return worker._run_sync(worker.agent.call("debug_state"))


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Dump the task timeline as Chrome-trace events (``ray timeline``
    analog; reference ``python/ray/_private/state.py:441,527``).  Load the
    written JSON in chrome://tracing or Perfetto."""
    from .util.state.api import StateApiClient, chrome_trace_events

    events = chrome_trace_events(
        StateApiClient().list_task_events(limit=100000)
    )
    if filename:
        import json as _json

        with open(filename, "w") as f:
            _json.dump(events, f)
    return events


def profile(event_name: str, extra: Optional[dict] = None):
    """Context manager recording a user profile span into the timeline
    (``ray.timeline`` profile-event analog)."""
    return global_worker().task_events.profile(event_name, extra)
