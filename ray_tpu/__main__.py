"""``python -m ray_tpu`` → the CLI (see ``ray_tpu/scripts/cli.py``)."""

import sys

from .scripts.cli import main

sys.exit(main())
