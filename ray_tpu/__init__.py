"""ray_tpu — a TPU-native distributed computing framework.

Tasks, actors, immutable shared-memory objects, and gang-scheduled placement
groups, where the scheduler's first-class resource is the TPU chip and the
TPU slice with its ICI topology; plus a JAX layer in which collectives lower
to XLA collectives over ICI and device tensors stay resident as jax.Arrays.

Public API mirrors the reference framework (see SURVEY.md):

    import ray_tpu

    ray_tpu.init()

    @ray_tpu.remote
    def f(x):
        return x * 2

    ray_tpu.get(f.remote(2))  # -> 4
"""

from ._version import __version__  # noqa: F401
from .api import (  # noqa: F401
    ActorClass,
    ActorHandle,
    ClientContext,
    ObjectRef,
    ObjectRefGenerator,
    PlacementGroup,
    RemoteFunction,
    SlicePlacementGroup,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    placement_group,
    placement_group_strategy,
    profile,
    put,
    remote,
    remove_placement_group,
    shutdown,
    state_summary,
    timeline,
    wait,
)
from .core.exceptions import (  # noqa: F401
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .core.node import Cluster  # noqa: F401
from .core.scheduler import (  # noqa: F401
    NodeAffinityStrategy,
    NodeLabelStrategy,
    SpreadStrategy,
)

def __getattr__(name):
    # `ray_tpu.dag` loads lazily (PEP 562): it pulls numpy at import
    # time, which costs ~0.2s of every WORKER cold start on a 1-core
    # host (any `ray_tpu.core.*` import runs this package __init__).
    if name == "dag":
        import importlib

        module = importlib.import_module(".dag", __name__)
        globals()["dag"] = module
        return module
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
