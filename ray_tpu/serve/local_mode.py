"""Local testing mode: run a deployment graph fully in-process.

``serve.run(app, local_testing_mode=True)`` constructs the deployments as
plain objects in this process — no cluster, no controller, no replica
actors — and returns a handle with the same call surface
(``.remote().result()``, method callers, streaming, composition).  An
async loop thread hosts coroutine methods so ``@serve.batch`` handlers
behave exactly as they do inside a replica.

Reference: ray ``python/ray/serve/local_testing_mode.py`` (the
``_LocalDeploymentHandle`` that wraps the user callable directly).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any, Dict, Optional

from .deployment import Application, Deployment

_registry: Dict[str, "LocalReplica"] = {}
_active = False  # a local-mode session ran; status()/delete() stay local
_loop: Optional[asyncio.AbstractEventLoop] = None
_loop_lock = threading.Lock()


def _ensure_loop() -> asyncio.AbstractEventLoop:
    """One background loop hosts every local deployment's async methods
    (the analog of the replica actor's event loop)."""
    global _loop
    with _loop_lock:
        if _loop is not None and not _loop.is_closed():
            return _loop
        loop = asyncio.new_event_loop()
        threading.Thread(
            target=loop.run_forever, daemon=True, name="serve-local"
        ).start()
        _loop = loop
        return loop


def _submit_thread(fn, *args, **kwargs):
    """Thread-per-call execution for sync methods.  A bounded pool would
    deadlock nested composition (a parent blocking on child.result()
    holds a pool thread the child then needs); local-mode call volume is
    test-sized, so a fresh daemon thread per call is the simple safe
    choice."""
    from concurrent.futures import Future

    fut: Future = Future()

    def run():
        try:
            fut.set_result(fn(*args, **kwargs))
        except BaseException as e:  # noqa: BLE001 — delivered to caller
            fut.set_exception(e)

    threading.Thread(target=run, daemon=True, name="serve-local").start()
    return fut


class LocalResponse:
    """Future-like, mirrors DeploymentResponse.  Execution is EAGER (the
    call is in flight the moment .remote() returns) — required for
    @serve.batch semantics, where concurrent in-flight calls form the
    batch."""

    def __init__(self, future):
        self._future = future

    def result(self, timeout: Optional[float] = 60.0):
        return self._future.result(timeout)

    @property
    def ref(self):
        raise RuntimeError("local testing mode has no ObjectRefs")


class LocalResponseGenerator:
    def __init__(self, gen):
        self._gen = gen

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)


class LocalReplica:
    def __init__(self, deployment: Deployment, init_args, init_kwargs):
        target = deployment.func_or_class
        self.deployment = deployment
        if inspect.isclass(target):
            self.instance = target(*init_args, **init_kwargs)
            self.is_function = False
        else:
            self.instance = target
            self.is_function = True

    def _resolve(self, method: str):
        if self.is_function:
            if method != "__call__":
                raise AttributeError(
                    f"function deployment has no method {method!r}"
                )
            return self.instance
        return getattr(self.instance, method)

    def submit(self, method: str, args, kwargs):
        """Start the call, return a concurrent.futures.Future."""
        fn = self._resolve(method)
        if asyncio.iscoroutinefunction(fn):
            return asyncio.run_coroutine_threadsafe(
                fn(*args, **kwargs), _ensure_loop()
            )
        return _submit_thread(fn, *args, **kwargs)

    def call_sync(self, method: str, args, kwargs):
        """Direct call (streaming path: the generator is the result)."""
        return self._resolve(method)(*args, **kwargs)


class _LocalMethodCaller:
    def __init__(self, handle: "LocalDeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method, args, kwargs)


class LocalDeploymentHandle:
    """Same call surface as DeploymentHandle, no cluster underneath."""

    def __init__(self, replica: LocalReplica, stream: bool = False):
        self._replica = replica
        self._stream = stream
        self.deployment_name = replica.deployment.name

    def options(self, *, stream: bool = False, **_ignored):
        return LocalDeploymentHandle(self._replica, stream=stream)

    def remote(self, *args, **kwargs):
        return self._invoke("__call__", args, kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _LocalMethodCaller(self, name)

    def _invoke(self, method: str, args, kwargs):
        if self._stream:
            gen = self._replica.call_sync(method, args, kwargs)
            if inspect.isasyncgen(gen):
                # Bridge an async-generator method to the sync iterator
                # surface (the cluster path supports async gens too).
                loop = _ensure_loop()

                def agen_iter():
                    while True:
                        try:
                            yield asyncio.run_coroutine_threadsafe(
                                gen.__anext__(), loop
                            ).result()
                        except StopAsyncIteration:
                            return

                return LocalResponseGenerator(agen_iter())
            return LocalResponseGenerator(iter(gen))
        return LocalResponse(self._replica.submit(method, args, kwargs))


def run_local(app) -> LocalDeploymentHandle:
    """Build + run an application graph in-process (children first, their
    handles injected into the parent constructor, like the cluster path)."""
    if isinstance(app, Deployment):
        app = Application(app)
    if not isinstance(app, Application):
        raise TypeError("serve.run expects an Application or Deployment")

    def convert(v):
        if isinstance(v, Deployment):
            v = Application(v)
        if isinstance(v, Application):
            return run_local(v)
        return v

    init_args = tuple(convert(a) for a in app.init_args)
    init_kwargs = {k: convert(v) for k, v in app.init_kwargs.items()}
    global _active
    _active = True
    replica = LocalReplica(app.deployment, init_args, init_kwargs)
    _registry[app.deployment.name] = replica
    return LocalDeploymentHandle(replica)


def get_local_handle(name: str) -> LocalDeploymentHandle:
    return LocalDeploymentHandle(_registry[name])


def local_status() -> Dict[str, Any]:
    return {
        name: {"num_replicas": 1, "status": "RUNNING"}
        for name in _registry
    }


def delete_local(name: str) -> bool:
    return _registry.pop(name, None) is not None


def shutdown_local():
    global _active
    _active = False
    _registry.clear()
