"""Pluggable replica routing policies for DeploymentHandles.

Reference: ray ``python/ray/serve/_private/request_router/pow_2_router.py``
(the default) and ``python/ray/llm/_internal/serve/routing_policies/
prefix_aware/`` (LLM serving: requests sharing a prompt prefix go to the
replica whose KV cache is warm for it, unless that replica is overloaded).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional

import ray_tpu


class ReplicaProbeError(Exception):
    """A replica queue probe failed — the handle force-refreshes its
    replica list and retries the route (a dead replica may be cached)."""


class RequestRouter:
    """Chooses a replica for one request.  May raise ReplicaProbeError to
    ask the handle for a fresh replica list."""

    def choose(self, replicas: List, args, kwargs):  # pragma: no cover
        raise NotImplementedError


class PowerOfTwoChoicesRouter(RequestRouter):
    """Probe two random replicas' queue depths, pick the shorter
    (reference ``pow_2_router.py:52``).  The ONE implementation of the
    default policy — DeploymentHandle delegates here too."""

    def choose(self, replicas: List, args, kwargs):
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        try:
            qa, qb = ray_tpu.get(
                [a.queue_len.remote(), b.queue_len.remote()], timeout=5
            )
        except Exception as e:
            raise ReplicaProbeError(str(e)) from e
        return a if qa <= qb else b


def _default_prompt_extractor(args, kwargs) -> Optional[str]:
    """Pull the prompt out of an OpenAI-style request body (the shapes the
    LLM app's endpoints receive)."""
    body = args[0] if args else kwargs.get("body")
    if isinstance(body, str):
        return body
    if isinstance(body, dict):
        if isinstance(body.get("prompt"), str):
            return body["prompt"]
        msgs = body.get("messages")
        if isinstance(msgs, list) and msgs:
            return "\x1e".join(
                str(m.get("content", "")) for m in msgs if isinstance(m, dict)
            )
    return None


class PrefixAwareRouter(RequestRouter):
    """Prefix-affinity routing with load protection.

    The first ``prefix_chars`` of the prompt key an affinity table mapping
    prefix → replica.  A hit routes back to the warm replica unless its
    queue is more than ``imbalance_factor`` deeper than the shortest
    replica's (then the request falls back to power-of-two and the prefix
    re-homes) — the reference's balanced-prefix-aware policy."""

    def __init__(
        self,
        prefix_chars: int = 64,
        imbalance_factor: float = 3.0,
        max_entries: int = 4096,
        prompt_extractor: Callable = _default_prompt_extractor,
    ):
        self.prefix_chars = prefix_chars
        self.imbalance_factor = imbalance_factor
        self.max_entries = max_entries
        self.extract = prompt_extractor
        self._affinity: Dict[str, Any] = {}  # prefix -> actor id
        self._fallback = PowerOfTwoChoicesRouter()
        # Cache-hit accounting: a "hit" is a warm-affinity route actually
        # taken (the request lands where its prefix KV is); re-homes and
        # cold prefixes are misses.  Published to the
        # ray_tpu_llm_prefix_cache_* counters under site="router".
        self.hits = 0
        self.misses = 0
        # Probing every replica per warm-prefix hit is O(n) RPCs on the hot
        # path; a short TTL bounds it to O(n) per interval (the reference's
        # bounded-probe design).  Queue depths staler than ~100 ms only
        # delay the re-home decision by one interval.
        self._lens_ttl_s = 0.1
        self._lens_cache: tuple = (0.0, None, None)  # (ts, replica_key, lens)

    def _queue_lens(self, replicas):
        import time as _time

        key = tuple(r._actor_id for r in replicas)
        ts, cached_key, lens = self._lens_cache
        now = _time.monotonic()
        if lens is not None and cached_key == key and now - ts < self._lens_ttl_s:
            return lens
        try:
            lens = ray_tpu.get(
                [r.queue_len.remote() for r in replicas], timeout=5
            )
        except Exception:
            return None
        self._lens_cache = (now, key, lens)
        return lens

    def _account(self, hit: bool):
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.record_llm_prefix_lookup("router", hit)
        except Exception:  # raylint: waive[RTL003] accounting must not fail routing
            pass

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self._affinity)}

    def choose(self, replicas: List, args, kwargs):
        prompt = self.extract(args, kwargs)
        if prompt is None or len(replicas) == 1:
            return (
                replicas[0]
                if len(replicas) == 1
                else self._fallback.choose(replicas, args, kwargs)
            )
        prefix = prompt[: self.prefix_chars]
        by_id = {r._actor_id: r for r in replicas}
        warm_id = self._affinity.get(prefix)
        warm = by_id.get(warm_id)
        chosen = None
        if warm is not None:
            lens = self._queue_lens(replicas)
            if lens is None:
                # A probe failure may mean the warm replica is dead —
                # surface it so the handle force-refreshes and retries
                # (returning warm here would poison the hot prefix).
                raise ReplicaProbeError("queue probes failed")
            warm_len = lens[replicas.index(warm)]
            min_len = min(lens)
            if warm_len <= max(self.imbalance_factor * max(min_len, 1), 1):
                self._account(True)
                return warm
            # Overloaded warm replica: we already hold every queue length —
            # take the shortest instead of re-probing two random ones.
            chosen = replicas[lens.index(min_len)]
        if chosen is None:
            chosen = self._fallback.choose(replicas, args, kwargs)
        self._account(False)
        if len(self._affinity) >= self.max_entries:
            self._affinity.pop(next(iter(self._affinity)))
        self._affinity[prefix] = chosen._actor_id
        return chosen
