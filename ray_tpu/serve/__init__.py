from .api import (  # noqa: F401
    delete,
    get_handle,
    run,
    shutdown,
    start_http_proxy,
    status,
)
from .batching import batch  # noqa: F401
from .deployment import Application, Deployment, deployment  # noqa: F401
from .handle import DeploymentHandle  # noqa: F401
