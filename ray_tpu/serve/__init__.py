from .api import (  # noqa: F401
    delete,
    deploy_config,
    get_handle,
    run,
    shutdown,
    start_http_proxy,
    status,
    stop_http_proxy,
)
from .grpc_ingress import start_grpc_ingress, stop_grpc_ingress  # noqa: F401
from .batching import batch  # noqa: F401
from .deployment import Application, Deployment, deployment  # noqa: F401
from .request_router import (  # noqa: F401
    PowerOfTwoChoicesRouter,
    PrefixAwareRouter,
    RequestRouter,
)
from .handle import (  # noqa: F401
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from .multiplex import get_multiplexed_model_id, multiplexed  # noqa: F401
