"""Serve public API: run/get_handle/status/delete/shutdown + HTTP ingress.

Reference: ray ``python/ray/serve/api.py:686`` (serve.run) and the per-node
proxy (``serve/_private/proxy.py``).  The HTTP proxy here is an aiohttp
server in the driver (or any) process routing ``POST <route_prefix>`` to the
deployment handle — one hop to the replica, controller out of the hot path,
matching the reference's proxy→router→replica design.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core.serialization import dumps_function

from .controller import CONTROLLER_NAME, ServeController
from .deployment import Application, Deployment
from .handle import DeploymentHandle

logger = logging.getLogger(__name__)

_http_state: Dict[str, Any] = {}


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return ServeController.options(
            # Long-poll listeners park an actor slot each for up to 30s
            # (one per subscribing process), on top of normal control calls.
            name=CONTROLLER_NAME, get_if_exists=True, max_concurrency=64
        ).remote()


def run(
    app,
    name: str = "",
    route_prefix: Optional[str] = None,
    local_testing_mode: bool = False,
) -> DeploymentHandle:
    """Deploy an Application (or bare Deployment) and return its handle.

    Composition: ``.bind()`` arguments may themselves be bound applications
    (``Pipeline.bind(model=Model.bind())``) — children deploy first and
    arrive in the parent's constructor as ``DeploymentHandle``s (reference:
    the deployment-graph build in ray ``serve/_private/build_app.py``).

    ``local_testing_mode=True`` runs the whole graph in THIS process — no
    cluster, no controller, no replica actors; the same handle surface
    backed by plain objects (reference: serve/local_testing_mode.py).
    """
    if local_testing_mode:
        from .local_mode import run_local

        return run_local(app)
    if isinstance(app, Deployment):
        app = Application(app)
    if not isinstance(app, Application):
        raise TypeError("serve.run expects an Application or Deployment")
    from ray_tpu.core.usage import record_library_usage

    record_library_usage("serve")
    controller = _get_or_create_controller()
    return _deploy_app(app, controller, route_prefix)


def _deploy_app(
    app: Application, controller, route_prefix: Optional[str] = None
) -> DeploymentHandle:
    def convert(v):
        if isinstance(v, Deployment):
            v = Application(v)
        if isinstance(v, Application):
            return _deploy_app(v, controller)
        return v

    init_args = tuple(convert(a) for a in app.init_args)
    init_kwargs = {k: convert(v) for k, v in app.init_kwargs.items()}
    d = app.deployment
    payload = dumps_function(d.func_or_class)
    ray_tpu.get(
        controller.deploy.remote(
            d.name,
            payload,
            init_args,
            init_kwargs,
            d.num_replicas,
            d.ray_actor_options,
            d.version,
            d.max_ongoing_requests,
            route_prefix or d.route_prefix,
            d.autoscaling_config,
        ),
        timeout=120,
    )
    return DeploymentHandle(d.name, controller)


def deploy_config(config: Dict[str, Any]) -> Dict[str, DeploymentHandle]:
    """Declarative multi-application deploy (reference: the REST config
    schema, ray ``serve/schema.py`` / ``serve deploy``).  Schema::

        {"applications": [
            {"import_path": "pkg.mod:app",   # Application or Deployment
             "route_prefix": "/x",           # optional
             "deployment_overrides": {"num_replicas": 2, ...}}  # optional
        ]}
    """
    import importlib

    handles: Dict[str, DeploymentHandle] = {}
    for spec in config.get("applications", []):
        mod_name, _, attr = spec["import_path"].partition(":")
        obj = getattr(importlib.import_module(mod_name), attr)
        if isinstance(obj, Deployment):
            obj = Application(obj)
        if not isinstance(obj, Application):
            raise TypeError(
                f"{spec['import_path']} is not an Application/Deployment"
            )
        overrides = spec.get("deployment_overrides")
        if overrides:
            obj = Application(
                obj.deployment.options(**overrides),
                obj.init_args,
                obj.init_kwargs,
            )
        handle = run(obj, route_prefix=spec.get("route_prefix"))
        handles[obj.deployment.name] = handle
    return handles


def get_handle(name: str) -> DeploymentHandle:
    from . import local_mode

    if name in local_mode._registry:
        return local_mode.get_local_handle(name)
    return DeploymentHandle(name)


def status() -> Dict[str, Any]:
    from . import local_mode

    if local_mode._active:
        return local_mode.local_status()
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=30)


def delete(name: str) -> bool:
    from . import local_mode

    if local_mode._active:
        return local_mode.delete_local(name)
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)


def shutdown():
    from .grpc_ingress import stop_grpc_ingress
    from .long_poll import reset_client
    from .local_mode import shutdown_local

    shutdown_local()
    reset_client()
    stop_http_proxy()
    stop_grpc_ingress()
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    for name in ray_tpu.get(controller.list_deployments.remote(), timeout=30):
        ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)
    ray_tpu.kill(controller)


# ------------------------------------------------------------------- HTTP
def start_http_proxy(host: str = "127.0.0.1", port: int = 8000) -> str:
    """Serve deployments over HTTP: POST <route_prefix> with a JSON body
    ``{"args": [...], "kwargs": {...}}`` (or any JSON object passed as the
    single argument)."""
    import asyncio

    from aiohttp import web

    controller = _get_or_create_controller()
    handles: Dict[str, DeploymentHandle] = {}
    # Route table: PUSHED by the controller's long-poll host (reference:
    # routes push to proxies via LongPollHost) — the controller stays out
    # of the request hot path and a deploy/delete is visible here within
    # one RPC latency.  Bootstrap: one direct pull before the first push.
    from .long_poll import long_poll_client

    lp = long_poll_client()
    lp.register(("routes",))
    route_bootstrap: Dict[str, Any] = {}
    route_bootstrap_miss: Dict[str, float] = {}

    async def get_routes_cached():
        pushed = lp.get(("routes",))
        if pushed is not None:
            return pushed
        # Pre-first-push: pull once and memoize even an EMPTY table (the
        # controller must stay out of the hot path for request streams
        # against a routeless proxy).  Off-loop (a blocking get here would
        # stall every in-flight request for up to the controller timeout —
        # raylint RTL005) and memoized as ONE shared task so concurrent
        # requests await the same pull instead of observing a
        # claimed-but-still-empty table and 404ing valid routes.
        fetch = route_bootstrap_miss.get("fetch")
        if fetch is None:

            async def _pull():
                try:
                    route_bootstrap.update(
                        await asyncio.get_running_loop().run_in_executor(
                            None,
                            lambda: ray_tpu.get(
                                controller.get_routes.remote(), timeout=30
                            ),
                        )
                    )
                except Exception as e:  # noqa: BLE001 — 404-repull recovers
                    logger.debug("route bootstrap pull failed: %s", e)

            fetch = asyncio.get_running_loop().create_task(_pull())
            route_bootstrap_miss["fetch"] = fetch
        # shield: one client disconnecting must not cancel the shared pull.
        await asyncio.shield(fetch)
        return route_bootstrap

    def match_route(path: str, routes: Dict[str, str]):
        # Longest-prefix match (reference route_prefix semantics): a
        # deployment at /v1 serves /v1/completions and /v1/chat/completions.
        name = routes.get(path)
        if name is None:
            candidates = [
                (prefix, n)
                for prefix, n in routes.items()
                if path.startswith(prefix.rstrip("/") + "/")
            ]
            if candidates:
                name = max(candidates, key=lambda c: len(c[0]))[1]
        return name

    async def stream_sse(request: "web.Request", handle, body, name=""):
        import asyncio as _asyncio
        import contextvars as _cv

        from ray_tpu.util import tracing

        # One request-scoped span covering the whole stream; the trace id
        # goes out as a response header so clients can fetch the stitched
        # cross-process trace (driver/proxy -> replica -> downstream).
        with tracing.start_span(
            "serve.http.stream", {"route": request.path, "deployment": name}
        ) as span:
            resp = web.StreamResponse(
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                    "x-ray-tpu-trace-id": span.trace_id,
                }
            )
            await resp.prepare(request)
            loop = _asyncio.get_running_loop()
            # Routing does blocking control-plane/replica probes — keep it
            # off the proxy loop (same as the non-stream path).  The copied
            # context carries the span into the executor thread so the
            # replica submission inherits the trace.
            ctx = _cv.copy_context()
            gen = await loop.run_in_executor(
                None,
                lambda: ctx.run(
                    lambda: handle.options(stream=True).remote(body)
                ),
            )
            sentinel = object()
            try:
                while True:
                    chunk = await loop.run_in_executor(
                        None, lambda: next(gen, sentinel)
                    )
                    if chunk is sentinel:
                        break
                    await resp.write(
                        b"data: " + json.dumps(chunk, default=str).encode()
                        + b"\n\n"
                    )
            except Exception as e:  # noqa: BLE001 — surface mid-stream errors
                span.set_attribute("error", str(e))
                await resp.write(
                    b"data: " + json.dumps({"error": str(e)}).encode()
                    + b"\n\n"
                )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp

    async def handle_request(request: "web.Request"):
        import time as _time

        name = match_route(request.path, await get_routes_cached())
        if name is None:
            # Route misses are usually real 404s (routes are PUSHED, so the
            # table is fresh); the one legit race is a deploy whose first
            # push hasn't landed.  One direct pull, rate-limited to once a
            # second so 404 streams never put the controller in the hot path.
            now = _time.monotonic()
            if now - route_bootstrap_miss.get("ts", 0.0) > 1.0:
                route_bootstrap_miss["ts"] = now
                try:
                    # Off-loop: a blocking get here would stall every
                    # in-flight request behind one controller round trip
                    # (raylint RTL005).
                    fresh = await asyncio.get_running_loop().run_in_executor(
                        None,
                        lambda: ray_tpu.get(
                            controller.get_routes.remote(), timeout=5
                        ),
                    )
                    route_bootstrap.clear()
                    route_bootstrap.update(fresh)
                    name = match_route(request.path, fresh)
                except Exception as e:  # noqa: BLE001 — fall through to 404
                    logger.debug("route bootstrap pull failed: %s", e)
        if name is None:
            return web.json_response(
                {"error": f"no deployment at {request.path}"}, status=404
            )
        handle = handles.setdefault(name, DeploymentHandle(name, controller))
        try:
            body = await request.json()
        except Exception:
            body = None
        if isinstance(body, dict) and body.get("stream") is True:
            # Server-sent events: the deployment's method must be a
            # generator; each chunk goes out as one `data:` frame
            # (reference: serve HTTP response streaming / OpenAI
            # `stream: true`).
            return await stream_sse(request, handle, body, name)
        if isinstance(body, dict) and ("args" in body or "kwargs" in body):
            args = body.get("args", [])
            kwargs = body.get("kwargs", {})
        elif body is None:
            args, kwargs = [], {}
        else:
            args, kwargs = [body], {}
        loop = asyncio.get_running_loop()
        from ray_tpu.util import tracing

        # Request-scoped span: the replica submission below happens
        # inside it, so the whole proxy -> replica -> downstream-task
        # path stitches into one trace (returned in the trace header).
        with tracing.start_span(
            "serve.http", {"route": request.path, "deployment": name}
        ) as span:
            headers = {"x-ray-tpu-trace-id": span.trace_id}
            response = handle.remote(*args, **kwargs)
            try:
                result = await loop.run_in_executor(
                    None, lambda: response.result(timeout=60)
                )
            except Exception as e:  # noqa: BLE001
                span.set_attribute("error", str(e))
                return web.json_response(
                    {"error": str(e)}, status=500, headers=headers
                )
        try:
            return web.json_response({"result": result}, headers=headers)
        except TypeError:
            return web.json_response({"result": repr(result)}, headers=headers)

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle_request)

    loop = asyncio.new_event_loop()
    started = threading.Event()
    runner_box = {}

    def serve_forever():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        runner_box["runner"] = runner
        started.set()
        loop.run_forever()

    t = threading.Thread(target=serve_forever, daemon=True, name="serve-http")
    t.start()
    started.wait(timeout=10)
    _http_state.update(loop=loop, thread=t, runner=runner_box.get("runner"))
    return f"http://{host}:{port}"


def stop_http_proxy():
    loop = _http_state.get("loop")
    if loop is not None:
        loop.call_soon_threadsafe(loop.stop)
        _http_state.clear()
