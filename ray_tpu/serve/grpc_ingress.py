"""gRPC ingress for serve deployments.

Reference: ray ``python/ray/serve/_private/proxy.py:534`` (``gRPCProxy``) —
a per-node gRPC server routing RPCs to deployment replicas alongside the
HTTP proxy.  Redesign: one generic service (no per-app protoc step),

    /ray_tpu.serve.Ingress/Call

taking a JSON request ``{"deployment": ..., "method": ..., "args": [...],
"kwargs": {...}}`` (deployment may instead be inferred from the
``route_prefix`` field) and returning JSON ``{"result": ...}``; errors map
to standard gRPC status codes.  Routing rides the same pushed route table
and DeploymentHandles (pow-2 / prefix-aware routers) as the HTTP proxy.
"""

from __future__ import annotations

import json
import logging
import threading
from concurrent import futures
from typing import Dict, Optional

logger = logging.getLogger(__name__)

SERVICE_METHOD = "/ray_tpu.serve.Ingress/Call"

_server = None
_server_lock = threading.Lock()


def start_grpc_ingress(host: str = "127.0.0.1", port: int = 9000,
                       max_workers: int = 8) -> str:
    """Start the node's gRPC ingress; returns ``host:port``."""
    global _server
    import grpc

    import ray_tpu

    from .handle import DeploymentHandle
    from .long_poll import long_poll_client

    lp = long_poll_client()
    lp.register(("routes",))
    handles: Dict[str, DeploymentHandle] = {}
    bootstrap: Dict[str, str] = {}
    bootstrap_state: Dict[str, float] = {}

    def routes_table() -> Dict[str, str]:
        pushed = lp.get(("routes",))
        if pushed is not None:
            return pushed
        # Pre-first-push window: one direct pull (mirrors the HTTP proxy's
        # bootstrap) so routes deployed before the ingress started resolve
        # immediately; rate-limited so 404 streams stay off the controller.
        import time as _time

        now = _time.monotonic()
        if now - bootstrap_state.get("ts", -10.0) > 1.0:
            bootstrap_state["ts"] = now
            try:
                import ray_tpu as _rt

                from .controller import CONTROLLER_NAME as _CN

                controller = _rt.get_actor(_CN)
                bootstrap.clear()
                bootstrap.update(
                    _rt.get(controller.get_routes.remote(), timeout=10)
                )
            except Exception:  # raylint: waive[RTL003] controller not up yet
                pass
        return bootstrap

    def resolve_deployment(req: dict) -> Optional[str]:
        name = req.get("deployment")
        if name:
            return name
        prefix = req.get("route_prefix")
        routes = routes_table()
        if prefix and prefix in routes:
            return routes[prefix]
        return None

    def call(request_bytes: bytes, context):
        try:
            req = json.loads(request_bytes or b"{}")
        except ValueError:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "request is not JSON"
            )
        name = resolve_deployment(req)
        if name is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"no deployment for {req.get('deployment') or req.get('route_prefix')!r}",
            )
        handle = handles.setdefault(name, DeploymentHandle(name))
        try:
            result = handle._invoke(
                req.get("method", "__call__"),
                tuple(req.get("args", ())),
                dict(req.get("kwargs", {})),
            ).result(timeout=req.get("timeout_s", 60.0))
        except Exception as e:  # noqa: BLE001 — map to gRPC status
            context.abort(grpc.StatusCode.INTERNAL, repr(e))
        try:
            return json.dumps({"result": result}).encode()
        except TypeError:
            context.abort(
                grpc.StatusCode.INTERNAL,
                f"result of type {type(result).__name__} is not "
                "JSON-serializable",
            )

    class Ingress(grpc.GenericRpcHandler):
        def service(self, details):
            if details.method == SERVICE_METHOD:
                return grpc.unary_unary_rpc_method_handler(
                    call,
                    request_deserializer=None,   # raw bytes
                    response_serializer=None,
                )
            return None

    with _server_lock:
        if _server is not None:
            stop_grpc_ingress()
        server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="grpc-ingress"
            )
        )
        server.add_generic_rpc_handlers((Ingress(),))
        bound = server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            raise OSError(f"could not bind gRPC ingress on {host}:{port}")
        server.start()
        _server = server
        _ = ray_tpu  # handle resolution happens lazily per call
        return f"{host}:{bound}"


def stop_grpc_ingress() -> None:
    global _server
    if _server is not None:
        try:
            _server.stop(grace=1.0)
        except Exception as e:
            logger.debug("grpc server stop failed: %s", e)
        _server = None
