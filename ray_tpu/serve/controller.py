"""Serve control plane: controller + replica actors.

Reference architecture (ray ``python/ray/serve/_private/controller.py:107``,
``deployment_state.py``, ``replica.py``): a singleton controller actor owns
deployment state and reconciles target vs. actual replica actors (versioned
in-place updates); replicas wrap the user callable and report queue depth
used by the router's power-of-two-choices.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, List

import ray_tpu
from ray_tpu.core.serialization import dumps_function, loads_function

CONTROLLER_NAME = "_serve_controller"


@ray_tpu.remote
class Replica:
    """Hosts one copy of the user callable."""

    def __init__(self, payload: bytes, init_args, init_kwargs):
        obj = loads_function(payload)
        if isinstance(obj, type):
            self.callable = obj(*init_args, **init_kwargs)
            self._is_class = True
        else:
            self.callable = obj
            self._is_class = False
        self._ongoing = 0
        self._lock = threading.Lock()
        self._total = 0

    def queue_len(self) -> int:
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        return {"ongoing": self._ongoing, "total": self._total}

    async def handle_request(self, method: str, args, kwargs):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if self._is_class:
                target = getattr(self.callable, method or "__call__")
            else:
                target = self.callable
            result = target(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = await result
            return result
        finally:
            with self._lock:
                self._ongoing -= 1

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def health_check(self) -> bool:
        if hasattr(self.callable, "check_health"):
            self.callable.check_health()
        return True


@ray_tpu.remote
class ServeController:
    """Singleton named actor owning all deployment state."""

    def __init__(self):
        # name -> {"spec": dict, "replicas": [handles], "version": str}
        self.deployments: Dict[str, dict] = {}

    def deploy(self, name: str, payload: bytes, init_args, init_kwargs,
               num_replicas: int, ray_actor_options: dict, version: str,
               max_ongoing_requests: int, route_prefix):
        import ray_tpu as rt

        entry = self.deployments.get(name)
        if entry is not None and entry["version"] != version:
            # Versioned update: replace replicas in place.
            for h in entry["replicas"]:
                try:
                    rt.kill(h)
                except Exception:
                    pass
            entry = None
        if entry is None:
            entry = {"replicas": [], "version": version}
        opts = dict(ray_actor_options or {})
        opts.setdefault("max_concurrency", max(2, max_ongoing_requests))
        current = len(entry["replicas"])
        if num_replicas > current:
            for _ in range(num_replicas - current):
                entry["replicas"].append(
                    Replica.options(**opts).remote(payload, init_args, init_kwargs)
                )
        elif num_replicas < current:
            for h in entry["replicas"][num_replicas:]:
                try:
                    rt.kill(h)
                except Exception:
                    pass
            entry["replicas"] = entry["replicas"][:num_replicas]
        entry["version"] = version
        entry["route_prefix"] = route_prefix or f"/{name}"
        entry["max_ongoing_requests"] = max_ongoing_requests
        self.deployments[name] = entry
        return {"name": name, "num_replicas": len(entry["replicas"])}

    def get_replicas(self, name: str) -> List:
        entry = self.deployments.get(name)
        if entry is None:
            raise KeyError(f"deployment {name!r} not found")
        return entry["replicas"]

    def get_routes(self) -> Dict[str, str]:
        return {
            e["route_prefix"]: name for name, e in self.deployments.items()
        }

    def delete_deployment(self, name: str) -> bool:
        import ray_tpu as rt

        entry = self.deployments.pop(name, None)
        if entry is None:
            return False
        for h in entry["replicas"]:
            try:
                rt.kill(h)
            except Exception:
                pass
        return True

    def status(self) -> Dict[str, Any]:
        return {
            name: {
                "num_replicas": len(e["replicas"]),
                "version": e["version"],
                "route_prefix": e["route_prefix"],
            }
            for name, e in self.deployments.items()
        }

    def list_deployments(self) -> List[str]:
        return list(self.deployments)
