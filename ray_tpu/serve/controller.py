"""Serve control plane: controller + replica actors.

Reference architecture (ray ``python/ray/serve/_private/controller.py:107``,
``deployment_state.py``, ``replica.py``, ``autoscaling_state.py``): a
singleton controller actor owns deployment state and runs a reconcile loop
that (a) replaces dead replicas and (b) autoscales replica counts from
queue metrics; replicas wrap the user callable and report queue depth used
by the router's power-of-two-choices.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core.serialization import loads_function
from ray_tpu.util.debug_locks import make_lock

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "_serve_controller"

_AUTOSCALE_DEFAULTS = {
    "min_replicas": 1,
    "max_replicas": 4,
    "target_ongoing_requests": 2.0,
    "upscale_delay_s": 0.5,
    "downscale_delay_s": 5.0,
    # Recorded-signal threshold (PR-10 per-request telemetry): sustained
    # window-mean queue wait above this upscales even when instantaneous
    # queue-depth probes look calm (queue wait integrates the pressure
    # the probes sample).  None disables the recorded signal.
    "target_queue_wait_s": 1.0,
    # Downscale is drain-then-retire: the replica leaves the routable
    # set immediately, keeps its in-flight work, and is killed when its
    # queue empties — or force-killed after this timeout.
    "drain_timeout_s": 30.0,
}


@ray_tpu.remote
class Replica:
    """Hosts one copy of the user callable."""

    def __init__(self, payload: bytes, init_args, init_kwargs,
                 max_ongoing_requests: int = 16,
                 deployment_name: str = ""):
        import os as _os

        obj = loads_function(payload)
        if isinstance(obj, type):
            self.callable = obj(*init_args, **init_kwargs)
            self._is_class = True
        else:
            self.callable = obj
            self._is_class = False
        self._ongoing = 0
        self._lock = make_lock("serve.replica.stats")
        self._total = 0
        # Per-request serving telemetry identity: TTFT / inter-token /
        # queue-wait histograms are tagged per deployment+replica.
        self._deployment = deployment_name or "anonymous"
        self._replica_tag = _os.urandom(3).hex()
        # User-request concurrency is gated HERE, not by actor-level
        # max_concurrency: system calls (queue_len / health_check) must
        # bypass the user queue or a saturated replica looks dead and its
        # metrics go dark (reference: replica system vs user concurrency).
        self._user_sem = asyncio.Semaphore(max(1, max_ongoing_requests))

    def queue_len(self) -> int:
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        return {"ongoing": self._ongoing, "total": self._total}

    async def handle_request(self, method: str, args, kwargs,
                             metadata: Optional[dict] = None):
        from . import multiplex
        from ray_tpu.util import flight_recorder, tracing

        t_arrive = time.perf_counter()
        with self._lock:
            # Counts queued + executing — the backlog signal autoscaling
            # and pow-2 routing want.
            self._ongoing += 1
            self._total += 1
        token = None
        if metadata and metadata.get("multiplexed_model_id") is not None:
            token = multiplex._model_id_var.set(
                metadata["multiplexed_model_id"]
            )
        await self._user_sem.acquire()
        queue_wait_s = time.perf_counter() - t_arrive
        outcome = "ok"
        try:
            with tracing.start_span(
                "serve.request",
                {"deployment": self._deployment,
                 "replica": self._replica_tag,
                 "method": method or "__call__"},
            ):
                if self._is_class:
                    target = getattr(self.callable, method or "__call__")
                else:
                    target = self.callable
                if asyncio.iscoroutinefunction(target):
                    result = target(*args, **kwargs)
                else:
                    # Sync callables must NOT run on the replica's event
                    # loop: a blocking call (e.g. composing another
                    # deployment handle's .result()) would deadlock the
                    # loop and trip the worker watchdog.
                    loop = asyncio.get_running_loop()
                    ctx = __import__("contextvars").copy_context()
                    result = await loop.run_in_executor(
                        None, lambda: ctx.run(target, *args, **kwargs)
                    )
                if inspect.iscoroutine(result):
                    # inspect, not asyncio: asyncio.iscoroutine() also
                    # matches plain generators (legacy @coroutine support
                    # on py<=3.11), and awaiting a user generator raises
                    # TypeError.
                    result = await result
                return result
        except BaseException:
            outcome = "error"
            raise
        finally:
            self._user_sem.release()
            try:
                flight_recorder.record_serve_request(
                    self._deployment, self._replica_tag, queue_wait_s,
                    time.perf_counter() - t_arrive, outcome=outcome,
                )
            except Exception:  # raylint: waive[RTL003] telemetry must not corrupt replica accounting
                pass
            if token is not None:
                multiplex._model_id_var.reset(token)
            with self._lock:
                self._ongoing -= 1

    async def handle_request_streaming(self, method: str, args, kwargs,
                                       metadata: Optional[dict] = None):
        """Streaming twin of handle_request: the target must be a generator
        (sync or async); each yielded chunk streams to the caller via the
        core runtime's streaming actor-method path."""
        from . import multiplex
        from ray_tpu.util import flight_recorder, tracing

        t_arrive = time.perf_counter()
        t_wall = time.time()
        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = None
        if metadata and metadata.get("multiplexed_model_id") is not None:
            token = multiplex._model_id_var.set(
                metadata["multiplexed_model_id"]
            )
        await self._user_sem.acquire()
        # Per-chunk cost stays an append; histograms land in one batch at
        # stream end (TTFT + every inter-chunk gap — the inter-token
        # stall distribution the serving SLOs gate on).
        tele = flight_recorder.StreamTelemetry(
            self._deployment, self._replica_tag,
            time.perf_counter() - t_arrive,
        )
        outcome = "ok"
        try:
            if self._is_class:
                target = getattr(self.callable, method or "__call__")
            else:
                target = self.callable
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                # e.g. an async __call__ that returns a generator when the
                # request asked for streaming.  inspect, not asyncio: a
                # SYNC generator target also lands here, and
                # asyncio.iscoroutine() matching it (legacy generator
                # coroutines, py<=3.11) would await-and-TypeError it.
                result = await result
            if hasattr(result, "__aiter__"):
                async for item in result:
                    tele.tick()
                    yield item
            elif hasattr(result, "__iter__"):
                # Sync generator: pull items on a thread so a blocking body
                # can't stall the replica loop.  Copy the context so the
                # multiplexed-model-id contextvar set above is visible
                # inside the generator frames (run_in_executor does not
                # propagate context by itself).
                import contextvars

                ctx = contextvars.copy_context()
                loop = asyncio.get_running_loop()
                sentinel = object()
                it = iter(result)
                while True:
                    item = await loop.run_in_executor(
                        None, lambda: ctx.run(next, it, sentinel)
                    )
                    if item is sentinel:
                        break
                    tele.tick()
                    yield item
            else:
                raise TypeError(
                    f"stream=True requires {method or '__call__'} to be a "
                    f"generator; got {type(result).__name__}"
                )
        except BaseException:
            outcome = "error"
            raise
        finally:
            self._user_sem.release()
            try:
                tele.done(outcome)
                # A completed span per stream (recorded, not opened, so
                # no contextvar crosses the generator's yields); parents
                # to the task:handle_request_streaming span when the
                # call is traced.
                tracing.record_span(
                    "serve.request.stream", t_wall, time.time(),
                    {"deployment": self._deployment,
                     "replica": self._replica_tag,
                     "ttft_s": tele.ttft_s,
                     "chunks": len(tele.gaps) + (1 if tele.ttft_s else 0),
                     "outcome": outcome},
                )
            except Exception:  # raylint: waive[RTL003] telemetry must not corrupt replica accounting
                pass
            if token is not None:
                multiplex._model_id_var.reset(token)
            with self._lock:
                self._ongoing -= 1

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def health_check(self) -> bool:
        if hasattr(self.callable, "check_health"):
            self.callable.check_health()
        return True


@ray_tpu.remote
class ServeController:
    """Singleton named actor owning all deployment state."""

    RECONCILE_PERIOD_S = 0.5

    def __init__(self):
        # name -> {"spec": {...}, "replicas": [handles], "version": str, ...}
        self.deployments: Dict[str, dict] = {}
        self._lock = make_lock("serve.controller.state")
        self._stop = threading.Event()
        # Long-poll host state (reference LongPollHost, serve/_private/
        # long_poll.py:252): per-key monotonically-increasing snapshot ids;
        # listeners block in listen_for_change until a key advances.
        # Mutations happen on actor calls AND the reconcile thread, so the
        # snapshot table is lock-guarded and waiters are asyncio events
        # woken via their owning loop.
        self._lp_lock = make_lock("serve.controller.long_poll")
        self._lp_snapshots: Dict[tuple, tuple] = {}  # key -> (id, value)
        self._lp_waiters: list = []  # [(loop, asyncio.Event)]
        # Recorded-signal state for autoscaling: a rate-limited snapshot
        # of the merged serving histograms, the per-deployment
        # (count, sum) watermark for window-delta queue-wait means, and
        # the last computed window mean (held between refreshes — the
        # snapshot TTL exceeds the reconcile period, and a None on
        # cached cycles would reset the sustain timer every round,
        # making the recorded signal unable to survive upscale_delay_s).
        self._serving_cache: Dict[str, Any] = {"ts": 0.0, "stats": {}}
        self._qw_prev: Dict[str, tuple] = {}
        self._qw_window: Dict[str, Optional[float]] = {}
        self._reconciler = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        )
        self._reconciler.start()

    # ----------------------------------------------------------- long poll
    def _publish(self, key: tuple, value) -> None:
        with self._lp_lock:
            next_id = self._lp_snapshots.get(key, (0, None))[0] + 1
            self._lp_snapshots[key] = (next_id, value)
            waiters, self._lp_waiters = self._lp_waiters, []
        for loop, ev in waiters:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass  # loop gone (shutdown)

    def _publish_state(self, name: Optional[str] = None) -> None:
        """Push the current replica list (for ``name``) and route table."""
        if name is not None:
            entry = self.deployments.get(name)
            self._publish(
                ("replicas", name),
                list(entry["replicas"]) if entry is not None else [],
            )
        self._publish(("routes",), self.get_routes())

    async def listen_for_change(
        self, keys_to_ids: Dict[tuple, int], timeout_s: float = 30.0
    ) -> Dict[tuple, tuple]:
        """Block until any subscribed key's snapshot id exceeds the
        client's, then return every advanced key's (id, snapshot).  Returns
        {} on timeout (client re-issues)."""
        import asyncio

        keys_to_ids = {tuple(k): v for k, v in keys_to_ids.items()}
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lp_lock:
                updates = {
                    k: self._lp_snapshots[k]
                    for k, i in keys_to_ids.items()
                    if k in self._lp_snapshots and self._lp_snapshots[k][0] > i
                }
                if updates:
                    return updates
                ev = asyncio.Event()
                self._lp_waiters.append((asyncio.get_running_loop(), ev))
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                with self._lp_lock:
                    self._lp_waiters = [
                        w for w in self._lp_waiters if w[1] is not ev
                    ]
                return {}
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                # Drop our waiter: timed-out listens must not accrete in
                # the host's waiter list on an idle cluster.
                with self._lp_lock:
                    self._lp_waiters = [
                        w for w in self._lp_waiters if w[1] is not ev
                    ]
                return {}

    # ------------------------------------------------------------- deploy API
    def deploy(self, name: str, payload: bytes, init_args, init_kwargs,
               num_replicas: int, ray_actor_options: dict, version: str,
               max_ongoing_requests: int, route_prefix,
               autoscaling_config: Optional[dict] = None):
        with self._lock:
            entry = self.deployments.get(name)
            if entry is not None and entry["version"] != version:
                # Versioned update: replace replicas in place.
                for h in entry["replicas"]:
                    self._kill(h)
                for h, _t0 in entry.get("draining", []):
                    self._kill(h)
                entry = None
            if entry is None:
                entry = {"replicas": [], "version": version}
            opts = dict(ray_actor_options or {})
            # Actor-level concurrency must never be the user-request gate:
            # queued handle_request coroutines waiting on _user_sem hold
            # actor slots, and system calls (queue_len/health_check) need a
            # slot immediately even when the replica is saturated.  So the
            # actor runs effectively unbounded and _user_sem alone caps
            # concurrent user work.
            opts.setdefault("max_concurrency", 1000)
            entry["spec"] = {
                "name": name,
                "payload": payload,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "opts": opts,
                "max_ongoing_requests": max_ongoing_requests,
            }
            entry["version"] = version
            # Normalize once at registration ('/v1/' == '/v1'); the proxy
            # does prefix matching against these keys.
            prefix = route_prefix or f"/{name}"
            entry["route_prefix"] = "/" + prefix.strip("/")
            entry["max_ongoing_requests"] = max_ongoing_requests
            if autoscaling_config is not None:
                entry["autoscaling"] = dict(
                    _AUTOSCALE_DEFAULTS, **autoscaling_config
                )
                num_replicas = max(
                    entry["autoscaling"]["min_replicas"],
                    min(num_replicas, entry["autoscaling"]["max_replicas"]),
                )
            else:
                entry.pop("autoscaling", None)
            entry["last_scale_ts"] = time.monotonic()
            entry["scale_pressure_since"] = None
            entry.setdefault("draining", [])  # [(handle, drain_start_ts)]
            self._set_replica_count(entry, num_replicas)
            self.deployments[name] = entry
            self._publish_state(name)
            return {"name": name, "num_replicas": len(entry["replicas"])}

    def _spawn_replica(self, entry: dict):
        spec = entry["spec"]
        return Replica.options(**spec["opts"]).remote(
            spec["payload"],
            spec["init_args"],
            spec["init_kwargs"],
            spec.get("max_ongoing_requests", 16),
            spec.get("name", ""),
        )

    def _set_replica_count(self, entry: dict, n: int,
                           drain: bool = False) -> None:
        current = len(entry["replicas"])
        if n > current:
            for _ in range(n - current):
                entry["replicas"].append(self._spawn_replica(entry))
        elif n < current:
            surplus = entry["replicas"][n:]
            entry["replicas"] = entry["replicas"][:n]
            if drain:
                # Drain-then-retire: out of the routable set now, killed
                # by _reap_draining once the queue empties (autoscale
                # downscales must not drop in-flight requests).
                now = time.monotonic()
                entry.setdefault("draining", []).extend(
                    (h, now) for h in surplus
                )
            else:
                for h in surplus:
                    self._kill(h)

    @staticmethod
    def _kill(handle) -> None:
        try:
            ray_tpu.kill(handle)
        except Exception as e:
            logger.debug("replica kill failed: %s", e)

    # --------------------------------------------------------- reconcile loop
    def _reconcile_loop(self):
        while not self._stop.wait(self.RECONCILE_PERIOD_S):
            try:
                self._reconcile_once()
            except Exception as e:  # noqa: BLE001
                logger.warning("serve reconcile round failed: %s", e)

    def _reconcile_once(self):
        with self._lock:
            entries = list(self.deployments.items())
        for name, entry in entries:
            self._replace_dead_replicas(name, entry)
            if "autoscaling" in entry:
                self._autoscale(name, entry)
            if entry.get("draining"):
                self._reap_draining(name, entry)

    # ---------------------------------------------- recorded queue-wait
    def _recorded_queue_wait(self, name: str) -> Optional[float]:
        """Window-delta mean of the recorded per-request queue-wait
        histogram for deployment ``name`` (the PR-10 serving telemetry) —
        the autoscaler's second signal next to instantaneous queue-depth
        probes.  Returns None when no new samples landed this window or
        the merged registry is unreachable."""
        now = time.monotonic()
        if now - self._serving_cache["ts"] > 2.0:
            try:
                from ray_tpu.util import obs

                self._serving_cache["stats"] = obs.serving_stats()
                self._serving_cache["ts"] = now
            except Exception as e:  # noqa: BLE001 — probes still autoscale
                logger.debug("serving-stats pull failed: %s", e)
                return self._qw_window.get(name)
            # Fresh snapshot: advance the watermark and recompute the
            # window mean for EVERY deployment in sight — only one
            # deployment's call triggers each refresh, and recomputing
            # just that one would leave the siblings' windows frozen
            # (None forever, or stuck at a stale high value that blocks
            # their downscale).  An idle window clears the value.
            stats = self._serving_cache["stats"]
            for dep in set(stats) | set(self._qw_prev):
                row = (stats.get(dep) or {}).get("queue_wait")
                if not row or not row.get("count"):
                    self._qw_window[dep] = None
                    continue
                count = row["count"]
                total = row.get("mean_s", 0.0) * count
                prev_count, prev_total = self._qw_prev.get(dep, (0, 0.0))
                self._qw_prev[dep] = (count, total)
                self._qw_window[dep] = (
                    (total - prev_total) / (count - prev_count)
                    if count > prev_count else None
                )
        # Held between refreshes so sustained pressure can out-live the
        # snapshot TTL and actually reach upscale_delay_s.
        return self._qw_window.get(name)

    def _reap_draining(self, name: str, entry: dict):
        """Retire draining replicas whose queues emptied; force-kill past
        the drain timeout.  Runs on the reconcile thread."""
        from ray_tpu.util import flight_recorder

        cfg = entry.get("autoscaling") or {}
        timeout = cfg.get("drain_timeout_s",
                          _AUTOSCALE_DEFAULTS["drain_timeout_s"])
        now = time.monotonic()
        keep = []
        events = []
        for h, t0 in list(entry.get("draining", [])):
            try:
                qlen = ray_tpu.get(h.queue_len.remote(), timeout=5)
            except Exception:  # noqa: BLE001 — dead already: reap it
                qlen = 0
            if qlen <= 0:
                self._kill(h)
                events.append("drain_retired")
            elif now - t0 > timeout:
                logger.warning(
                    "deployment %s: force-killing draining replica with %d "
                    "requests still queued after %.0fs", name, qlen, timeout,
                )
                self._kill(h)
                events.append("drain_forced")
            else:
                keep.append((h, t0))
        with self._lock:
            if self.deployments.get(name) is not entry:
                return
            entry["draining"] = keep
        for direction in events:
            flight_recorder.record_serve_autoscale(
                name, direction, len(entry["replicas"]) + len(keep)
            )

    def _replace_dead_replicas(self, name: str, entry: dict):
        """Health check every replica; respawn the dead (reference:
        DeploymentState reconciling target vs. actual).  Checks are issued
        concurrently up-front; each replica then gets an INDEPENDENT
        ``serve_health_check_timeout_s`` budget measured from its own
        await — one stuck replica consuming its full window must not
        starve later replicas down to a floor where a merely-slow-but-
        healthy co-deployed replica accumulates spurious strikes and gets
        replaced (worst-case sweep time is n_stuck x timeout, which the
        consecutive-failure threshold already bounds in practice).
        Respawn revalidates the entry under the lock — deploy()/delete()
        may have replaced it while the (slow) checks ran."""
        from ray_tpu.core.config import GlobalConfig

        replicas = list(entry["replicas"])
        refs = [(h, h.health_check.remote()) for h in replicas]
        per_replica_timeout = GlobalConfig.serve_health_check_timeout_s
        fails = entry.setdefault("_health_fails", {})
        # Keyed by the STABLE actor id, and pruned to live replicas each
        # sweep: an id(handle) key would leak strikes across downscales,
        # and CPython id() reuse could charge a fresh replica with a dead
        # predecessor's count — killing it on its first slow (tolerated)
        # health check.
        live = {h._actor_id.hex() for h in replicas}
        for key in [k for k in fails if k not in live]:
            del fails[key]
        dead = []
        for h, ref in refs:
            hid = h._actor_id.hex()
            try:
                ray_tpu.get(ref, timeout=per_replica_timeout)
                fails.pop(hid, None)
            except Exception as e:  # noqa: BLE001
                # Tolerate consecutive timeouts before replacing
                # (reference: serve replica health uses a 30s+ budget):
                # a replica compiling its first jax program holds the GIL
                # for tens of seconds — busy-but-alive, and killing it
                # fails the very request that triggered the compile.  An
                # actor that is actually DEAD fails fast (dead-actor
                # error), not by timeout — replace it immediately.
                from ray_tpu.core.exceptions import GetTimeoutError

                if isinstance(e, GetTimeoutError):
                    n = fails.get(hid, 0) + 1
                    fails[hid] = n
                    if n < GlobalConfig.serve_health_failure_threshold:
                        continue
                dead.append(h)
                fails.pop(hid, None)
        if not dead:
            return
        with self._lock:
            if self.deployments.get(name) is not entry:
                return  # entry was redeployed/deleted while we checked
            for h in dead:
                try:
                    idx = entry["replicas"].index(h)
                except ValueError:
                    continue  # already scaled away
                logger.warning(
                    "deployment %s replica %d unhealthy; replacing", name, idx
                )
                self._kill(h)
                entry["replicas"][idx] = self._spawn_replica(entry)
            self._publish_state(name)

    def _autoscale(self, name: str, entry: dict):
        """Scale replica counts from TWO signals: instantaneous queue-
        depth probes (reference pow-2 metric) and the recorded window-mean
        queue wait (PR-10 per-request histograms — pressure the probes
        can sample past).  Up on sustained pressure from either; down via
        drain-then-retire on sustained starvation."""
        from ray_tpu.util import flight_recorder

        cfg = entry["autoscaling"]
        replicas = entry["replicas"]
        if not replicas:
            return
        try:
            queue_lens = ray_tpu.get(
                [h.queue_len.remote() for h in replicas], timeout=5
            )
        except Exception:  # noqa: BLE001 — dead replicas handled above
            return
        per_replica = sum(queue_lens) / len(replicas)
        target = cfg["target_ongoing_requests"]
        qw_target = cfg.get("target_queue_wait_s")
        qw_mean = (
            self._recorded_queue_wait(name) if qw_target is not None else None
        )
        qw_pressure = qw_mean is not None and qw_mean > qw_target
        now = time.monotonic()
        desired = None
        direction = None
        if (per_replica > target or qw_pressure) and (
            len(replicas) < cfg["max_replicas"]
        ):
            if entry["scale_pressure_since"] is None:
                entry["scale_pressure_since"] = now
            if now - entry["scale_pressure_since"] >= cfg["upscale_delay_s"]:
                desired = min(
                    cfg["max_replicas"],
                    max(
                        len(replicas) + 1,
                        int(len(replicas) * per_replica / target),
                    ),
                )
                direction = "up"
        elif (
            per_replica < target * 0.5
            and not qw_pressure
            and len(replicas) > cfg["min_replicas"]
        ):
            if entry["scale_pressure_since"] is None:
                entry["scale_pressure_since"] = now
            if now - entry["scale_pressure_since"] >= cfg["downscale_delay_s"]:
                desired = max(cfg["min_replicas"], len(replicas) - 1)
                direction = "down"
        else:
            entry["scale_pressure_since"] = None
        if desired is not None and desired != len(replicas):
            logger.info(
                "autoscaling %s: %d -> %d (avg ongoing %.2f, target %.2f, "
                "queue-wait window mean %s)",
                name, len(replicas), desired, per_replica, target,
                f"{qw_mean:.3f}s" if qw_mean is not None else "n/a",
            )
            with self._lock:
                if self.deployments.get(name) is not entry:
                    return
                self._set_replica_count(entry, desired,
                                        drain=direction == "down")
                entry["scale_pressure_since"] = None
                entry["last_scale_ts"] = now
                self._publish_state(name)
                total = len(entry["replicas"]) + len(entry.get("draining", []))
            flight_recorder.record_serve_autoscale(name, direction, total)

    def remediation_scale_up(self, name: str) -> Dict[str, Any]:
        """SLO-remediation nudge: one replica up through the same
        bookkeeping the reconcile-loop autoscaler uses (max_replicas
        clamp, pressure-timer reset, state publish, autoscale-event
        recording) — the remediation controller's queue-pressure
        actuator.  Idempotent at the max: declines instead of
        overshooting, so a finding re-delivered every beat cannot grow
        the fleet past the deployment's own bound."""
        from ray_tpu.util import flight_recorder

        with self._lock:
            entry = self.deployments.get(name)
            if entry is None:
                return {"scaled": False, "reason": f"unknown deployment {name!r}"}
            cfg = entry.get("autoscaling") or _AUTOSCALE_DEFAULTS
            current = len(entry["replicas"])
            if current >= cfg["max_replicas"]:
                # The decline carries the replica resource shape so the
                # remediation controller's fair-share fallback knows what
                # bundle to free (preempt low-priority training) instead
                # of just giving up — see util/remediation.py.
                opts = (entry.get("spec") or {}).get("opts") or {}
                return {"scaled": False, "replicas": current,
                        "reason": f"at max_replicas={cfg['max_replicas']}",
                        "replica_resources": dict(
                            opts.get("resources") or {"CPU": 1.0}
                        )}
            self._set_replica_count(entry, current + 1)
            entry["scale_pressure_since"] = None
            entry["last_scale_ts"] = time.monotonic()
            self._publish_state(name)
            total = len(entry["replicas"]) + len(entry.get("draining", []))
        flight_recorder.record_serve_autoscale(name, "up", total)
        logger.info(
            "remediation scale-up: deployment %s %d -> %d replicas",
            name, current, current + 1,
        )
        return {"scaled": True, "replicas": current + 1}

    # -------------------------------------------------------------- query API
    def get_replicas(self, name: str) -> List:
        entry = self.deployments.get(name)
        if entry is None:
            raise KeyError(f"deployment {name!r} not found")
        return list(entry["replicas"])

    def get_routes(self) -> Dict[str, str]:
        return {
            e["route_prefix"]: name for name, e in self.deployments.items()
        }

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            entry = self.deployments.pop(name, None)
            if entry is None:
                return False
            for h in entry["replicas"]:
                self._kill(h)
            for h, _t0 in entry.get("draining", []):
                self._kill(h)
            self._publish_state(name)
            return True

    def status(self) -> Dict[str, Any]:
        return {
            name: {
                "num_replicas": len(e["replicas"]),
                "num_draining": len(e.get("draining", [])),
                "version": e["version"],
                "route_prefix": e["route_prefix"],
                "autoscaling": e.get("autoscaling"),
            }
            for name, e in self.deployments.items()
        }

    def list_deployments(self) -> List[str]:
        return list(self.deployments)
