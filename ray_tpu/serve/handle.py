"""DeploymentHandle + router.

Reference: ray ``python/ray/serve/handle.py:757`` → ``router.py:881`` →
``request_router/pow_2_router.py:52`` — requests route to the replica with
the shorter queue among two random candidates (power of two choices).
"""

from __future__ import annotations

import random
import time
from typing import Any, List, Optional

import ray_tpu

from .controller import CONTROLLER_NAME


class DeploymentResponse:
    """Future-like wrapper over the replica call's ObjectRef."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = 60.0):
        return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment call's chunks (reference:
    ``handle.options(stream=True)``); yields VALUES, one per chunk the
    replica's generator produced."""

    def __init__(self, ref_generator, timeout: Optional[float] = 120.0):
        self._gen = ref_generator
        self._timeout = timeout

    def __iter__(self):
        return self

    def __next__(self):
        ref = next(self._gen)
        return ray_tpu.get(ref, timeout=self._timeout)


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._invoke(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller=None,
                 multiplexed_model_id: Optional[str] = None,
                 stream: bool = False):
        self.deployment_name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._refreshed = 0.0
        self._rr = 0
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        # Pluggable routing policy (reference: request_router/); None =
        # the built-in power-of-two-choices in _pick_replica.
        self._router = None
        # model_id -> actor id of the replica that last served it (session
        # affinity — the reference's multiplex-aware router prefers replicas
        # already holding the model).
        self._model_affinity: dict = {}

    _UNSET = object()

    def options(self, *, multiplexed_model_id=_UNSET,
                stream=_UNSET, request_router=_UNSET) -> "DeploymentHandle":
        """Chaining-safe: options not passed keep their current values
        (``h.options(multiplexed_model_id="m").options(stream=True)``
        retains the model id)."""
        clone = DeploymentHandle(
            self.deployment_name,
            self._controller,
            self._multiplexed_model_id
            if multiplexed_model_id is self._UNSET
            else multiplexed_model_id,
            self._stream if stream is self._UNSET else stream,
        )
        clone._replicas = self._replicas
        clone._refreshed = self._refreshed
        clone._model_affinity = self._model_affinity
        clone._router = (
            self._router if request_router is self._UNSET else request_router
        )
        return clone

    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh(self, force=False):
        """Replica list updates are PUSHED by the controller's long-poll
        host (reference ``LongPollHost``): the process-wide client holds
        one blocking listen; this method just reads its latest snapshot —
        no periodic polling, and a killed replica's removal lands here
        within one RPC latency.  ``force`` (probe-failure recovery) and
        first use bootstrap with a direct RPC."""
        from .long_poll import long_poll_client

        key = ("replicas", self.deployment_name)
        client = long_poll_client()
        client.register(key)
        if not force:
            pushed = client.get(key)
            if pushed is not None:
                self._replicas = pushed
                return
            if self._replicas:
                return  # bootstrap copy still valid until a push lands
        self._replicas = ray_tpu.get(
            self._get_controller().get_replicas.remote(self.deployment_name),
            timeout=30,
        )
        self._refreshed = time.monotonic()

    def _pick_replica(self, args=(), kwargs=None):
        """Route via the configured RequestRouter (default: power-of-two
        choices by queue depth).  On a probe failure the replica list is
        force-refreshed once and the route retried (a cached dead replica
        must not poison routing until the next periodic refresh)."""
        from .request_router import PowerOfTwoChoicesRouter, ReplicaProbeError

        router = self._router
        if router is None:
            router = self.__dict__.setdefault(
                "_default_router", PowerOfTwoChoicesRouter()
            )
        kwargs = kwargs or {}
        for attempt in (0, 1):
            self._refresh(force=attempt > 0)
            if not self._replicas:
                # A pushed EMPTY list can be the stale delete snapshot of
                # a just-redeployed deployment (delete publishes [], the
                # redeploy's push may not have landed) — ask the
                # controller directly before declaring it empty.
                if attempt == 0:
                    continue
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no replicas"
                )
            try:
                return router.choose(self._replicas, args, kwargs)
            except ReplicaProbeError:
                if attempt:
                    self._rr += 1
                    return self._replicas[self._rr % len(self._replicas)]

    def _invoke(self, method: str, args, kwargs) -> DeploymentResponse:
        model_id = self._multiplexed_model_id
        replica = None
        if model_id is not None:
            # Session affinity: route back to the replica that has the model.
            sticky = self._model_affinity.get(model_id)
            self._refresh()
            for r in self._replicas:
                if r._actor_id == sticky:
                    replica = r
                    break
            if replica is not None:
                try:  # liveness probe — the cached list may be stale
                    ray_tpu.get(replica.queue_len.remote(), timeout=3)
                except Exception:  # noqa: BLE001
                    self._model_affinity.pop(model_id, None)
                    self._refresh(force=True)
                    replica = None
        if replica is None:
            replica = self._pick_replica(args, kwargs)
            if model_id is not None:
                self._model_affinity[model_id] = replica._actor_id
        self._rr += 1
        metadata = (
            {"multiplexed_model_id": model_id} if model_id is not None else None
        )
        if self._stream:
            gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, args, kwargs, metadata)
            return DeploymentResponseGenerator(gen)
        ref = replica.handle_request.remote(method, args, kwargs, metadata)
        return DeploymentResponse(ref)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._invoke("__call__", args, kwargs)

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,))
