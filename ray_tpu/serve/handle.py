"""DeploymentHandle + router.

Reference: ray ``python/ray/serve/handle.py:757`` → ``router.py:881`` →
``request_router/pow_2_router.py:52`` — requests route to the replica with
the shorter queue among two random candidates (power of two choices).
"""

from __future__ import annotations

import random
import time
from typing import Any, List, Optional

import ray_tpu

from .controller import CONTROLLER_NAME

_REPLICA_REFRESH_S = 5.0


class DeploymentResponse:
    """Future-like wrapper over the replica call's ObjectRef."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = 60.0):
        return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._invoke(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller=None):
        self.deployment_name = deployment_name
        self._controller = controller
        self._replicas: List = []
        self._refreshed = 0.0
        self._rr = 0

    def _get_controller(self):
        if self._controller is None:
            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _refresh(self, force=False):
        now = time.monotonic()
        if force or not self._replicas or now - self._refreshed > _REPLICA_REFRESH_S:
            self._replicas = ray_tpu.get(
                self._get_controller().get_replicas.remote(self.deployment_name),
                timeout=30,
            )
            self._refreshed = now

    def _pick_replica(self):
        """Power-of-two-choices by queue depth (2+ replicas), else direct."""
        self._refresh()
        if not self._replicas:
            raise RuntimeError(
                f"deployment {self.deployment_name!r} has no replicas"
            )
        if len(self._replicas) == 1:
            return self._replicas[0]
        a, b = random.sample(self._replicas, 2)
        try:
            qa, qb = ray_tpu.get(
                [a.queue_len.remote(), b.queue_len.remote()], timeout=5
            )
        except Exception:
            self._refresh(force=True)
            return self._replicas[self._rr % len(self._replicas)]
        return a if qa <= qb else b

    def _invoke(self, method: str, args, kwargs) -> DeploymentResponse:
        replica = self._pick_replica()
        self._rr += 1
        ref = replica.handle_request.remote(method, args, kwargs)
        return DeploymentResponse(ref)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._invoke("__call__", args, kwargs)

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name,))
