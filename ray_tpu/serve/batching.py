"""Dynamic request batching (reference: ray ``python/ray/serve/batching.py``
— ``@serve.batch`` collects concurrent calls into one batched invocation).

Usage inside a deployment class (the wrapped method receives a list of the
queued single-call arguments and must return a list of results):

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01)
    async def infer(self, inputs):  # inputs: List[x]
        return model(np.stack(inputs)).tolist()
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, List


class _Batcher:
    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self._queue: List = []  # (arg, future)
        self._flusher: asyncio.Task | None = None

    async def submit(self, owner, arg):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._queue.append((arg, fut))
        if len(self._queue) >= self.max_batch_size:
            await self._flush(owner)
        elif self._flusher is None or self._flusher.done():
            self._flusher = loop.create_task(self._delayed_flush(owner))
        return await fut

    async def _delayed_flush(self, owner):
        await asyncio.sleep(self.timeout_s)
        await self._flush(owner)

    async def _flush(self, owner):
        if not self._queue:
            return
        batch, self._queue = self._queue, []
        args = [a for a, _ in batch]
        try:
            if owner is not None:
                results = await self.fn(owner, args)
            else:
                results = await self.fn(args)
            if len(results) != len(args):
                raise ValueError(
                    f"batched function returned {len(results)} results for "
                    f"{len(args)} inputs"
                )
            for (_, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as e:  # noqa: BLE001
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    def wrap(fn):
        batcher = _Batcher(fn, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:  # bound method: (self, arg)
                owner, arg = args
            else:
                owner, arg = None, args[0]
            return await batcher.submit(owner, arg)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
