"""Model multiplexing: many models LRU-cached across a pool of replicas.

Reference: ray ``python/ray/serve/multiplex.py`` — ``@serve.multiplexed``
wraps an async model loader with a per-replica LRU; the request's model id
rides handle metadata (``handle.options(multiplexed_model_id=...)``) and is
readable inside the replica via ``serve.get_multiplexed_model_id()``.  The
router prefers replicas that already hold the model (session affinity in
``DeploymentHandle``).
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import logging
from collections import OrderedDict
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

_model_id_var: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "rtpu_serve_multiplexed_model_id", default=None
)


def get_multiplexed_model_id() -> Optional[str]:
    """Inside a replica: the model id of the current request (or None)."""
    return _model_id_var.get()


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate an async ``get_model(self, model_id)`` loader.  Calls are
    LRU-cached per replica; evicted models get ``__del__``/``unload``
    called if defined."""

    def wrap(fn: Callable):
        @functools.wraps(fn)
        async def wrapper(self, model_id: str):
            from ray_tpu.util import flight_recorder

            cache: OrderedDict = getattr(self, "_rtpu_mux_cache", None)
            if cache is None:
                cache = OrderedDict()
                self._rtpu_mux_cache = cache
                self._rtpu_mux_locks = {}
            # Fast path: cache hits never wait on another model's load.
            if model_id in cache:
                cache.move_to_end(model_id)
                flight_recorder.record_mux_cache_event("hit")
                return cache[model_id]
            # Per-model lock: concurrent requests for the SAME new model
            # load once; different models load in parallel.
            lock = self._rtpu_mux_locks.setdefault(model_id, asyncio.Lock())
            async with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    flight_recorder.record_mux_cache_event("hit")
                    return cache[model_id]
                flight_recorder.record_mux_cache_event("miss")
                model = fn(self, model_id)
                if asyncio.iscoroutine(model):
                    model = await model
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    evicted_id, evicted = cache.popitem(last=False)
                    flight_recorder.record_mux_cache_event("eviction")
                    self._rtpu_mux_locks.pop(evicted_id, None)
                    unload = getattr(evicted, "unload", None)
                    if callable(unload):
                        try:
                            result = unload()
                            if asyncio.iscoroutine(result):
                                await result
                        except Exception as e:
                            logger.warning("model unload failed: %s", e)
                return model

        wrapper._is_serve_multiplexed = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
