"""Deployments — the declarative unit of serving.

Reference surface: ray ``python/ray/serve/deployment.py`` +
``serve/api.py`` — ``@serve.deployment`` wraps a class or function with
replica/resource options; ``.bind(*args)`` produces an Application deployed
by ``serve.run``.  TPU-first: ``ray_actor_options={"num_tpus": 1}`` packs
replicas one-per-chip (chip isolation via the lease's TPU_VISIBLE_CHIPS).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass
class Deployment:
    name: str
    func_or_class: Any
    num_replicas: int = 1
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    max_ongoing_requests: int = 16
    version: str = "1"
    route_prefix: Optional[str] = None
    # {"min_replicas", "max_replicas", "target_ongoing_requests",
    #  "upscale_delay_s", "downscale_delay_s"} — reference
    # ``serve/autoscaling_policy.py`` defaults.
    autoscaling_config: Optional[Dict[str, Any]] = None

    def options(self, **kwargs) -> "Deployment":
        merged = dataclasses.asdict(self)
        merged.pop("func_or_class", None)
        merged.update(kwargs)
        return Deployment(func_or_class=self.func_or_class, **merged)

    def bind(self, *init_args, **init_kwargs) -> "Application":
        return Application(self, init_args, init_kwargs)


@dataclasses.dataclass
class Application:
    deployment: Deployment
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               max_ongoing_requests: int = 16,
               version: str = "1",
               route_prefix: Optional[str] = None,
               autoscaling_config: Optional[Dict[str, Any]] = None):
    """``@serve.deployment`` decorator."""

    def wrap(obj) -> Deployment:
        return Deployment(
            name=name or getattr(obj, "__name__", "deployment"),
            func_or_class=obj,
            num_replicas=num_replicas,
            ray_actor_options=ray_actor_options or {},
            max_ongoing_requests=max_ongoing_requests,
            version=version,
            route_prefix=route_prefix,
            autoscaling_config=autoscaling_config,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
