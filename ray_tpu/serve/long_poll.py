"""Long-poll push of serve control state to handles and proxies.

Reference: ray ``python/ray/serve/_private/long_poll.py:252`` —
``LongPollHost`` on the controller holds per-key snapshot ids; clients
issue a blocking ``listen_for_change({key: last_seen_id})`` RPC that
returns as soon as any key advances.  Route tables and replica lists
propagate in one RPC latency instead of a poll period, and a killed
replica's removal is *pushed* to every router.

Host side lives in ``ServeController`` (``listen_for_change`` +
``_publish_state``); this module is the client: one daemon thread per
process multiplexes every handle/proxy subscription in that process over
a single outstanding listen call.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..util.debug_locks import make_lock

logger = logging.getLogger(__name__)

LISTEN_TIMEOUT_S = 30.0


class LongPollClient:
    """Per-process multiplexing client for the controller's long-poll host."""

    def __init__(self, controller_name: str):
        self._controller_name = controller_name
        self._known: Dict[Tuple, Tuple[int, Any]] = {}
        self._keys: set = set()
        self._lock = make_lock("serve.long_poll.client")
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    def register(self, key: Tuple) -> None:
        with self._lock:
            if key in self._keys:
                return
            self._keys.add(key)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._listen_loop, daemon=True,
                    name="serve-long-poll",
                )
                self._thread.start()

    def get(self, key: Tuple):
        """Latest pushed snapshot for ``key`` (None until the first push)."""
        entry = self._known.get(key)
        return entry[1] if entry is not None else None

    def stop(self) -> None:
        self._stopped = True

    # ------------------------------------------------------------- internals
    def _listen_loop(self) -> None:
        import ray_tpu

        controller = None
        while not self._stopped:
            try:
                if controller is None:
                    controller = ray_tpu.get_actor(self._controller_name)
                with self._lock:
                    keys_to_ids = {
                        k: self._known.get(k, (0, None))[0]
                        for k in self._keys
                    }
                updates = ray_tpu.get(
                    controller.listen_for_change.remote(
                        keys_to_ids, LISTEN_TIMEOUT_S
                    ),
                    timeout=LISTEN_TIMEOUT_S + 15,
                )
                if updates:
                    with self._lock:
                        self._known.update(updates)
            except Exception as e:  # noqa: BLE001 — controller restart etc.
                if self._stopped:
                    return
                logger.debug("long-poll listen failed (%s); retrying", e)
                controller = None
                time.sleep(0.5)


_client: Optional[LongPollClient] = None
_client_lock = make_lock("serve.long_poll.singleton")


def long_poll_client() -> LongPollClient:
    """Process-wide client (one listen loop no matter how many handles)."""
    global _client
    with _client_lock:
        if _client is None or _client._stopped:
            from .controller import CONTROLLER_NAME

            _client = LongPollClient(CONTROLLER_NAME)
        return _client


def reset_client() -> None:
    """Drop the process client (serve shutdown / tests)."""
    global _client
    with _client_lock:
        if _client is not None:
            _client.stop()
            _client = None
