"""Simulated node agent: the control-plane-facing half of a node,
without workers.

``bench.py limits`` needs the control plane's *scale envelope* — how
many node agents it can carry through a leader failover — but spawning
64+ REAL agents (each with worker pools, shm arenas, object
directories) would exhaust a laptop long before the control plane is
the bottleneck.  A ``SimNodeAgent`` speaks the full agent wire
protocol (register/heartbeat/re-register with ``held_pgs``, the bundle
two-phase-commit batch RPCs, actor-worker creation) against the real
control plane, but execution is fake: "workers" are synthetic
addresses that are never connected to, and resource accounting is a
plain dict.  The chaos boundary is the node's execution half —
everything CP-side (scheduling, journaling, lease failover, client
re-anchor through ``make_cp_resolver``) is production code.

Run as a subprocess fleet (``python -m ray_tpu.devtools.sim_agent``);
each process dies with its parent via the reaper watchdog, so a killed
bench cannot leak a 64-process fleet.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
from typing import Dict, Optional

from ..core.config import GlobalConfig
from ..core.cp_ha import make_cp_resolver
from ..core.ids import NodeID
from ..core.rpc import RetryableRpcClient, RpcServer

logger = logging.getLogger(__name__)


class SimNodeAgent:
    """Agent-protocol endpoint with dict-based resource accounting and
    no worker processes.  Unknown CP→agent RPCs are acked benignly (a
    sim node has nothing to remediate, prestart, or evict)."""

    def __init__(self, host: str, port: int, cp_address: str,
                 session_id: str, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 cp_ha_dir: Optional[str] = None):
        self.node_id = NodeID.from_random()
        self.session_id = session_id
        self.total = dict(resources)
        self.available = dict(resources)
        self.labels = dict(labels or {})
        self.cp_ha_dir = cp_ha_dir
        resolver = (
            make_cp_resolver(cp_ha_dir, cp_address) if cp_ha_dir else None
        )
        self.cp_client = RetryableRpcClient(
            cp_address, address_resolver=resolver
        )
        self.server = RpcServer(self, host, port)
        # pg_id -> summed reservation (the real agent tracks per-bundle
        # pools; the CP only ever observes the aggregate + held_pgs).
        self.bundles: Dict[object, Dict[str, float]] = {}
        self.workers: Dict[str, Dict[str, float]] = {}
        self._worker_seq = 0
        self.registrations = 0   # register_node round-trips (incl. re-reg)
        self._hb_task: Optional[asyncio.Task] = None

    # ---------------------------------------------------------- lifecycle
    async def start(self) -> str:
        addr = await self.server.start()
        await self._register(addr)
        self._hb_task = asyncio.get_running_loop().create_task(
            self._heartbeat_loop()
        )
        logger.info("sim agent %s on %s", self.node_id.hex()[:8], addr)
        return addr

    async def stop(self):
        if self._hb_task is not None:
            self._hb_task.cancel()
        await self.server.stop()
        await self.cp_client.close()

    async def _register(self, addr: str):
        reply = await self.cp_client.call(
            "register_node",
            {
                "node_id": self.node_id,
                "agent_address": addr,
                "snapshot": self._snapshot(),
                "held_pgs": list(self.bundles),
            },
        )
        assert reply["ok"]
        # Reconciliation: groups the CP removed/evicted while this node
        # (or the CP itself) was away must release their reservations.
        for pg_id in reply.get("drop_pgs") or ():
            self._drop_pg(pg_id)
        self.registrations += 1

    def _snapshot(self) -> dict:
        return {
            "total": dict(self.total),
            "available": dict(self.available),
            "labels": dict(self.labels),
            "pending_demands": [],
            "idle_s": 0.0,
        }

    async def _heartbeat_loop(self):
        period = GlobalConfig.health_check_period_s
        while True:
            try:
                reply = await self.cp_client.call(
                    "heartbeat",
                    {"node_id": self.node_id, "snapshot": self._snapshot()},
                    retries=1,
                )
                if reply.get("reregister"):
                    # A fresh leader (or restarted CP) lost the volatile
                    # node table: replay registration with held_pgs so it
                    # can reconcile reservations against its journal.
                    await self._register(self.server.address)
            except Exception as e:  # noqa: BLE001 — leaderless windows are expected
                logger.debug("sim heartbeat failed: %s", e)
            await asyncio.sleep(period)

    # -------------------------------------------------- resource accounting
    def _reserve(self, need: Dict[str, float]) -> bool:
        for k, v in need.items():
            if self.available.get(k, 0.0) + 1e-9 < v:
                return False
        for k, v in need.items():
            self.available[k] = self.available.get(k, 0.0) - v
        return True

    def _release(self, held: Dict[str, float]):
        for k, v in held.items():
            self.available[k] = min(
                self.total.get(k, 0.0), self.available.get(k, 0.0) + v
            )

    def _prepare_pg(self, pg_id, bundles: Dict[int, Dict[str, float]]) -> bool:
        # ``bundles`` is the agent wire shape: {bundle_index: resource spec}.
        need: Dict[str, float] = {}
        for b in bundles.values():
            for k, v in b.items():
                need[k] = need.get(k, 0.0) + v
        if not self._reserve(need):
            return False
        prev = self.bundles.get(pg_id)
        if prev is not None:
            self._release(prev)
        self.bundles[pg_id] = need
        return True

    def _drop_pg(self, pg_id):
        held = self.bundles.pop(pg_id, None)
        if held:
            self._release(held)

    # ----------------------------------------------------- agent protocol
    def handle_ping(self, payload, conn):
        return "pong"

    def handle_prepare_bundles_batch(self, payload, conn):
        return {
            "results": {
                g["pg_id"]: self._prepare_pg(g["pg_id"], g["bundles"])
                for g in payload["groups"]
            }
        }

    handle_reserve_bundles_batch = handle_prepare_bundles_batch

    def handle_prepare_bundles(self, payload, conn):
        return {"ok": self._prepare_pg(payload["pg_id"], payload["bundles"])}

    def handle_commit_bundles(self, payload, conn):
        return True

    def handle_commit_bundles_batch(self, payload, conn):
        return True

    def handle_cancel_bundles(self, payload, conn):
        self._drop_pg(payload["pg_id"])
        return True

    def handle_cancel_bundles_batch(self, payload, conn):
        for pg_id in payload["pg_ids"]:
            self._drop_pg(pg_id)
        return True

    handle_return_bundles = handle_cancel_bundles
    handle_return_bundles_batch = handle_cancel_bundles_batch

    async def handle_create_actor_worker(self, payload, conn):
        spec = payload["spec"]
        need = dict(spec.resources)
        if spec.placement_group_id is None and not self._reserve(need):
            raise ValueError("insufficient resources for actor")
        self._worker_seq += 1
        addr = f"sim-{self.node_id.hex()[:8]}:{self._worker_seq}"
        self.workers[addr] = (
            need if spec.placement_group_id is None else {}
        )
        return {"worker_address": addr}

    async def handle_kill_worker(self, payload, conn):
        held = self.workers.pop(payload.get("worker_address"), None)
        if held:
            self._release(held)
        return True

    async def handle_prepare_evict(self, payload, conn):
        return {"acks": 0, "workers": 0}

    def handle_list_objects(self, payload, conn):
        return []

    def handle_free_objects(self, payload, conn):
        return True

    def handle_prestart_pool(self, payload, conn):
        return True

    async def handle_remediate(self, payload, conn):
        return {"ok": True, "results": []}

    def handle_debug_state(self, payload, conn):
        return {
            "node_id": self.node_id.hex(),
            "registrations": self.registrations,
            "held_pgs": len(self.bundles),
            "workers": len(self.workers),
            "available": dict(self.available),
        }

    def on_connection_closed(self, conn):
        return None


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--cp-address", required=True)
    parser.add_argument("--session-id", required=True)
    parser.add_argument("--resources", required=True, help="JSON dict")
    parser.add_argument("--labels", default="{}", help="JSON dict")
    parser.add_argument("--cp-ha-dir", default=None)
    parser.add_argument("--ready-file", default=None,
                        help="written with the bound address once registered")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    from ..core.reaper import watch_parent_process

    watch_parent_process()

    async def run():
        agent = SimNodeAgent(
            args.host,
            args.port,
            args.cp_address,
            args.session_id,
            json.loads(args.resources),
            json.loads(args.labels),
            cp_ha_dir=args.cp_ha_dir,
        )
        addr = await agent.start()
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(addr)
            os.replace(tmp, args.ready_file)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(run())


if __name__ == "__main__":
    main()
