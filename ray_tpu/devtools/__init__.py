"""Developer tooling for the ray_tpu runtime.

``python -m ray_tpu.devtools.lint`` — ``raylint``, the runtime-invariant
static analyzer (rules RTL001–RTL006, see ``docs/static_analysis.md``).
Its dynamic companion, the ``RAY_TPU_DEBUG_LOCKS=1`` lock-order cycle
detector, lives in ``ray_tpu.util.debug_locks``.
"""
