"""raylint — runtime-invariant static analysis for the ray_tpu codebase.

The runtime is a multi-threaded Python system whose concurrency and
error-handling invariants (no blocking under a lock, no silent exception
swallowing, bounded waits so PR-1's overload degradation can engage) were
previously enforced only by convention.  This analyzer makes them
mergeable-or-not, the role TSAN/clang-tidy wiring plays for the reference
runtime's C++ core.

Usage::

    python -m ray_tpu.devtools.lint [paths...]
    python -m ray_tpu.devtools.lint --list-rules

With no paths, lints the ``ray_tpu`` package this module was imported
from.  Exit status: 0 clean, 1 unwaived violations, 2 usage/parse error.

Rules (stable IDs; full prose in ``docs/static_analysis.md``):

  RTL001 no-blocking-under-lock   blocking calls inside ``with <lock>:``
  RTL002 thread-hygiene           Thread() must pass daemon= and name=
  RTL003 swallowed-exception      ``except Exception: pass`` must justify
  RTL004 metric-name-registry     ray_tpu_* names declared once + documented
  RTL005 async-blocking           no time.sleep / blocking get in async def
  RTL006 untimed-wait             Condition/Event.wait() & queue get need
                                  timeouts on runtime paths

Waivers: a checked-in ``lint_waivers.toml`` next to this module
grandfathers specific sites (each entry carries a reason and date), and
an inline ``# raylint: waive[RTL00X] why`` comment on the flagged line
waives one site in place.  Unwaived violations fail the run; unused
waiver entries are reported so the file stays minimal.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "RTL000": "parse-error",  # not waivable: an unparseable file is never OK
    "RTL001": "no-blocking-under-lock",
    "RTL002": "thread-hygiene",
    "RTL003": "swallowed-exception",
    "RTL004": "metric-name-registry",
    "RTL005": "async-blocking",
    "RTL006": "untimed-wait",
}

# Rules whose scope is "runtime paths": the concurrency-sensitive layers.
# Files outside a ray_tpu package (e.g. test fixture snippets) are treated
# as runtime scope so every rule is exercisable on a standalone file.
RUNTIME_SCOPE_PREFIXES = (
    "core/", "serve/", "util/", "dag/", "collective/", "autoscaler/",
)
RUNTIME_SCOPE_FILES = ("dashboard.py",)

_LOCK_NAME_RE = re.compile(
    r"(^|_)(lock|locks|cv|cond|condition|mutex)(_|$)|lock$", re.IGNORECASE
)
_QUEUE_NAME_RE = re.compile(
    r"(^|_)(q|queue|queues|chan|channel|inbox|mailbox)(_|$)|queue$",
    re.IGNORECASE,
)
_METRIC_NAME_RE = re.compile(r"ray_tpu_[a-z0-9_]+")
_WAIVE_COMMENT_RE = re.compile(
    r"#\s*raylint:\s*waive\[([A-Z0-9,\s]+)\]"
)


class Violation:
    __slots__ = ("rule", "path", "line", "col", "message", "waived",
                 "waive_source")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.waived = False
        self.waive_source = ""

    def render(self) -> str:
        tag = f" [waived: {self.waive_source}]" if self.waived else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{RULES[self.rule]}: {self.message}{tag}")


# --------------------------------------------------------------- waivers
class WaiverError(Exception):
    pass


class Waiver:
    __slots__ = ("rules", "path", "contains", "line", "reason", "date",
                 "used")

    def __init__(self, rules: Sequence[str], path: str,
                 contains: Optional[str], line: Optional[int],
                 reason: str, date: str):
        self.rules = tuple(rules)
        self.path = path.replace(os.sep, "/")
        self.contains = contains
        self.line = line
        self.reason = reason
        self.date = date
        self.used = False

    def matches(self, v: Violation, source_line: str) -> bool:
        if v.rule not in self.rules:
            return False
        # Suffix match with a path-component boundary: a waiver for
        # "core/rpc.py" must not also cover "score/rpc.py".
        vpath = v.path.replace(os.sep, "/")
        if vpath != self.path and not vpath.endswith("/" + self.path):
            return False
        if self.line is not None and self.line != v.line:
            return False
        if self.contains is not None and self.contains not in source_line:
            return False
        return True


def parse_waivers(path: str) -> List[Waiver]:
    """Parse the waiver file: a TOML subset (``[[waiver]]`` tables of
    string/int assignments) — parsed by hand because the runtime targets
    interpreters without ``tomllib`` and must not grow dependencies."""
    waivers: List[Waiver] = []
    current: Optional[dict] = None

    def finish(entry: Optional[dict], at_line: int):
        if entry is None:
            return
        missing = [k for k in ("rule", "path", "reason", "date")
                   if k not in entry]
        if missing:
            raise WaiverError(
                f"{path}: waiver ending at line {at_line} is missing "
                f"required field(s): {', '.join(missing)}"
            )
        rules = [r.strip() for r in entry["rule"].split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            raise WaiverError(
                f"{path}: waiver ending at line {at_line} names unknown "
                f"rule(s): {', '.join(unknown)}"
            )
        line_no = entry.get("line")
        if line_no is not None:
            line_no = int(line_no)
        waivers.append(Waiver(rules, entry["path"], entry.get("contains"),
                              line_no, entry["reason"], entry["date"]))

    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[waiver]]":
                finish(current, i)
                current = {}
                continue
            m = re.match(
                r'^([A-Za-z_]+)\s*=\s*(?:"((?:[^"\\]|\\.)*)"|(\d+))\s*'
                r"(?:#.*)?$", line,
            )
            if m is None or current is None:
                raise WaiverError(
                    f"{path}:{i}: unparseable waiver line: {line!r} "
                    "(expected [[waiver]] tables of key = \"string\" or "
                    "key = integer assignments)"
                )
            key, s_val, i_val = m.group(1), m.group(2), m.group(3)
            current[key] = (
                int(i_val) if i_val is not None
                else s_val.encode().decode("unicode_escape")
            )
        finish(current, i if waivers or current else 0)
    return waivers


# ------------------------------------------------------------ AST helpers
def _terminal_name(node: ast.AST) -> Optional[str]:
    """`self._tier_lock` -> "_tier_lock", `lock` -> "lock"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """`time.sleep` -> "time.sleep"; gives up on non-trivial bases."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_lock_expr(node: ast.AST) -> bool:
    name = _terminal_name(node)
    if name is None:
        # threading.Lock() acquired inline: `with threading.Lock():`
        if isinstance(node, ast.Call):
            dn = _dotted(node.func) or ""
            return dn.split(".")[-1] in ("Lock", "RLock", "Condition")
        return False
    return bool(_LOCK_NAME_RE.search(name))


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _body_nodes_no_nested_defs(body: Sequence[ast.stmt]):
    """Yield every node in ``body`` without descending into nested
    function/class definitions (their execution escapes the lock/async
    context being analyzed)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _blocking_call_reason(node: ast.Call) -> Optional[str]:
    """If ``node`` is one of the calls RTL001/RTL005 forbid, say why."""
    dn = _dotted(node.func)
    if dn == "time.sleep":
        return "time.sleep() blocks the holder"
    if dn is not None and (dn.startswith("subprocess.")):
        return f"{dn}() forks/blocks on a child process"
    if dn in ("ray_tpu.get", "ray.get"):
        return f"{dn}() is a distributed blocking get"
    if isinstance(node.func, ast.Attribute) and node.func.attr == "result":
        return ".result() blocks on a future"
    return None


def _is_untimed_wait(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute) or _has_kw(node, "timeout"):
        return False
    if node.func.attr == "wait":
        return not node.args
    if node.func.attr == "wait_for":
        # Condition.wait_for(predicate) loops an untimed wait() inside;
        # asyncio.wait_for(aw, t) carries its timeout as 2nd positional.
        return len(node.args) <= 1
    return False


def _is_untimed_queue_get(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"):
        return False
    recv = _terminal_name(node.func.value)
    if recv is None or not _QUEUE_NAME_RE.search(recv):
        return False
    if _has_kw(node, "timeout"):
        return False
    # Non-blocking try-gets raise Empty immediately — bounded by nature.
    for kw in node.keywords:
        if (kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False):
            return False
    if (node.args and isinstance(node.args[0], ast.Constant)
            and node.args[0].value is False):
        return False
    # q.get() / q.get(True) / q.get(block=True) — all unbounded.
    positional_timeout = len(node.args) >= 2
    return not positional_timeout


# ------------------------------------------------------------- the checker
class FileChecker(ast.NodeVisitor):
    def __init__(self, path: str, source: str, runtime_scope: bool,
                 declared_metrics: Set[str], registry_file: bool):
        self.path = path
        self.source_lines = source.splitlines()
        self.runtime_scope = runtime_scope
        self.declared_metrics = declared_metrics
        self.registry_file = registry_file
        self.violations: List[Violation] = []
        self._awaited: Set[int] = set()
        self._async_depth = 0
        self._thread_ctors: Set[str] = {"threading.Thread", "Thread"}

    # -- plumbing ---------------------------------------------------------
    def check(self) -> List[Violation]:
        try:
            tree = ast.parse("\n".join(self.source_lines), filename=self.path)
        except SyntaxError as e:
            self._add("RTL000", e.lineno or 1, 0,
                      f"file does not parse: {e.msg}")
            return self.violations
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                # `import threading as _t` -> match `_t.Thread(...)` too.
                for alias in node.names:
                    if alias.name == "threading" and alias.asname:
                        self._thread_ctors.add(f"{alias.asname}.Thread")
            elif isinstance(node, ast.ImportFrom):
                # `from threading import Thread as Thr` -> match `Thr(...)`.
                if node.module == "threading":
                    for alias in node.names:
                        if alias.name == "Thread":
                            self._thread_ctors.add(
                                alias.asname or alias.name
                            )
            elif isinstance(node, ast.Await):
                self._awaited.add(id(node.value))
            elif isinstance(node, ast.Call):
                # `asyncio.wait_for(ev.wait(), timeout)` bounds the inner
                # wait — exempt its arguments from the untimed-wait rule.
                dn = _dotted(node.func) or ""
                if dn.split(".")[-1] == "wait_for":
                    for arg in node.args:
                        self._awaited.add(id(arg))
        self.visit(tree)
        return self.violations

    def _add(self, rule: str, line: int, col: int, message: str):
        self.violations.append(Violation(rule, self.path, line, col, message))

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1]
        return ""

    # -- RTL001 -----------------------------------------------------------
    def _check_with(self, node):
        if not self.runtime_scope:
            return
        lock_items = [
            item for item in node.items
            if _is_lock_expr(item.context_expr)
        ]
        if not lock_items:
            return
        lock_desc = _terminal_name(lock_items[0].context_expr) or "lock"
        for inner in _body_nodes_no_nested_defs(node.body):
            if not isinstance(inner, ast.Call):
                continue
            reason = _blocking_call_reason(inner)
            if reason is None and _is_untimed_wait(inner):
                reason = "untimed .wait() parks the thread with the lock" \
                         " context in scope"
            if reason is not None:
                self._add(
                    "RTL001", inner.lineno, inner.col_offset,
                    f"blocking call inside `with {lock_desc}:` — {reason}; "
                    "move it outside the critical section",
                )

    def visit_With(self, node: ast.With):
        self._check_with(node)
        self.generic_visit(node)

    # -- RTL002 -----------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        dn = _dotted(node.func) or ""
        if dn in self._thread_ctors:
            missing = [kw for kw in ("daemon", "name")
                       if not _has_kw(node, kw)]
            if missing:
                self._add(
                    "RTL002", node.lineno, node.col_offset,
                    "threading.Thread(...) must set "
                    f"{' and '.join(m + '=' for m in missing)} explicitly "
                    "(unnamed/implicit-daemon threads are undebuggable "
                    "and can block interpreter exit)",
                )
        # -- RTL005 / RTL006 (call-shaped rules) --------------------------
        if self.runtime_scope:
            if self._async_depth > 0:
                reason = _blocking_call_reason(node)
                if reason is not None and not dn.startswith("subprocess."):
                    # subprocess is RTL001's concern; async bodies care
                    # about anything that parks the event loop thread.
                    self._add(
                        "RTL005", node.lineno, node.col_offset,
                        f"blocking call in async def — {reason}; the event "
                        "loop (and every coroutine on it) stalls. Use the "
                        "async equivalent or run_in_executor",
                    )
            if id(node) not in self._awaited:
                if _is_untimed_wait(node):
                    self._add(
                        "RTL006", node.lineno, node.col_offset,
                        "untimed .wait(): a lost notify or wedged peer "
                        "hangs this thread forever — pass a timeout and "
                        "re-check the predicate",
                    )
                elif _is_untimed_queue_get(node):
                    self._add(
                        "RTL006", node.lineno, node.col_offset,
                        "unbounded queue get(): pass timeout= so overload "
                        "degrades into a timeout error instead of a hang",
                    )
        self.generic_visit(node)

    # -- RTL003 -----------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self.runtime_scope and self._is_broad_handler(node):
            body = [
                stmt for stmt in node.body
                if not (isinstance(stmt, ast.Expr)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str))
            ]
            if len(body) == 1 and isinstance(body[0], ast.Pass):
                self._add(
                    "RTL003", node.lineno, node.col_offset,
                    "broad except with a pass-only body swallows every "
                    "failure silently — log it, count it via "
                    "util/metrics.py, or waive with a justification",
                )
        self.generic_visit(node)

    @staticmethod
    def _is_broad_handler(node: ast.ExceptHandler) -> bool:
        if node.type is None:
            return True
        types = (node.type.elts if isinstance(node.type, ast.Tuple)
                 else [node.type])
        for t in types:
            name = _terminal_name(t)
            if name in ("Exception", "BaseException"):
                return True
        return False

    # -- RTL004 -----------------------------------------------------------
    def visit_Constant(self, node: ast.Constant):
        if (isinstance(node.value, str)
                and not self.registry_file
                and _METRIC_NAME_RE.fullmatch(node.value)
                and node.value not in self.declared_metrics):
            self._add(
                "RTL004", node.lineno, node.col_offset,
                f"metric name {node.value!r} is not declared in "
                "ray_tpu/util/metric_registry.py — declare it there (and "
                "document it in docs/observability.md), then import the "
                "constant",
            )
        self.generic_visit(node)

    # -- async tracking (RTL005) ------------------------------------------
    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._async_depth += 1
        self.generic_visit(node)
        self._async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef):
        # A sync def nested in an async def runs on its own thread/stack.
        saved, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = saved

    def visit_Lambda(self, node: ast.Lambda):
        # Same: a lambda handed to run_in_executor executes off-loop.
        saved, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = saved

    def visit_AsyncWith(self, node: ast.AsyncWith):
        # `async with lock:` is an asyncio lock — blocking calls under it
        # stall the loop, which RTL005 already reports per call site.
        self.generic_visit(node)


# ---------------------------------------------------------- file discovery
def _iter_python_files(paths: Sequence[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            raise FileNotFoundError(p)


def _package_relative(path: str) -> Optional[str]:
    """Path inside the ray_tpu package ('core/foo.py'), or None if the
    file is not under a ray_tpu directory."""
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "ray_tpu" not in parts:
        return None
    idx = len(parts) - 1 - parts[::-1].index("ray_tpu")
    rel = "/".join(parts[idx + 1:])
    return rel or None


def _in_runtime_scope(path: str) -> bool:
    rel = _package_relative(path)
    if rel is None:
        return True  # standalone snippet (fixtures): all rules apply
    return (rel.startswith(RUNTIME_SCOPE_PREFIXES)
            or rel in RUNTIME_SCOPE_FILES)


def _registry_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "util", "metric_registry.py")


def load_declared_metrics(registry_path: Optional[str] = None) -> Set[str]:
    """Metric names declared in the registry module — parsed from its AST
    so linting never imports runtime code."""
    registry_path = registry_path or _registry_path()
    declared: Set[str] = set()
    try:
        with open(registry_path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=registry_path)
    except (OSError, SyntaxError):
        return declared
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and _METRIC_NAME_RE.fullmatch(node.value)):
            declared.add(node.value)
    return declared


def check_docs_coverage(declared: Set[str],
                        doc_path: Optional[str] = None) -> List[Violation]:
    """RTL004 second half: every registered name must appear in
    docs/observability.md (skipped silently when the docs tree is not
    present, e.g. an installed wheel)."""
    registry = _registry_path()
    if doc_path is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        doc_path = os.path.join(repo_root, "docs", "observability.md")
    if not os.path.isfile(doc_path):
        return []
    with open(doc_path, encoding="utf-8") as f:
        doc_text = f.read()
    out = []
    for name in sorted(declared):
        if name not in doc_text:
            out.append(Violation(
                "RTL004", registry, 1, 0,
                f"metric {name!r} is registered but undocumented — add it "
                f"to {os.path.relpath(doc_path)}",
            ))
    return out


# ----------------------------------------------------------------- driver
def _inline_waive_rules(line_text: str) -> Set[str]:
    m = _WAIVE_COMMENT_RE.search(line_text)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def run(paths: Sequence[str], waiver_file: Optional[str],
        check_docs: bool = True) -> Tuple[List[Violation], List[Waiver]]:
    declared = load_declared_metrics()
    registry = _registry_path()
    waivers = parse_waivers(waiver_file) if waiver_file else []
    violations: List[Violation] = []
    checkers: Dict[str, FileChecker] = {}

    for path in _iter_python_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        checker = FileChecker(
            path, source, _in_runtime_scope(path), declared,
            registry_file=os.path.abspath(path) == registry,
        )
        checkers[path] = checker
        violations.extend(checker.check())

    if check_docs:
        violations.extend(check_docs_coverage(declared))

    for v in violations:
        if v.rule == "RTL000":
            continue  # parse failures are never waivable
        checker = checkers.get(v.path)
        line_text = checker.source_line(v.line) if checker else ""
        if v.rule in _inline_waive_rules(line_text):
            v.waived = True
            v.waive_source = "inline comment"
            continue
        for w in waivers:
            if w.matches(v, line_text):
                v.waived = True
                v.waive_source = f"waiver file ({w.date}: {w.reason})"
                w.used = True
                break
    return violations, waivers


def default_waiver_file() -> Optional[str]:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_waivers.toml")
    return path if os.path.isfile(path) else None


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="raylint: runtime-invariant static analysis "
                    "(RTL001-RTL006)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "ray_tpu package)")
    parser.add_argument("--waivers", default=None,
                        help="waiver file (default: lint_waivers.toml "
                             "next to this module)")
    parser.add_argument("--no-waivers", action="store_true",
                        help="ignore the waiver file (show everything)")
    parser.add_argument("--no-docs-check", action="store_true",
                        help="skip the RTL004 docs-coverage pass")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--show-waived", action="store_true",
                        help="also print waived violations")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, slug in RULES.items():
            print(f"{rule_id}  {slug}")
        return 0

    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    waiver_file = None if args.no_waivers else (
        args.waivers or default_waiver_file()
    )
    try:
        violations, waivers = run(paths, waiver_file,
                                  check_docs=not args.no_docs_check)
    except (WaiverError, FileNotFoundError) as e:
        print(f"raylint: error: {e}", file=sys.stderr)
        return 2

    unwaived = [v for v in violations if not v.waived]
    shown = violations if args.show_waived else unwaived
    for v in sorted(shown, key=lambda v: (v.path, v.line, v.rule)):
        print(v.render())
    # Unused-waiver nagging only makes sense for a whole-package run — a
    # subset lint legitimately never exercises most entries.
    if not args.paths:
        for w in waivers:
            if not w.used:
                print(f"raylint: warning: unused waiver "
                      f"({','.join(w.rules)} {w.path}) — remove it",
                      file=sys.stderr)
    n_waived = sum(1 for v in violations if v.waived)
    print(f"raylint: {len(unwaived)} violation(s), {n_waived} waived",
          file=sys.stderr)
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main())
