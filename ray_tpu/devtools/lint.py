"""raylint — runtime-invariant static analysis for the ray_tpu codebase.

The runtime is a multi-threaded Python system whose concurrency and
error-handling invariants (no blocking under a lock, no silent exception
swallowing, bounded waits so PR-1's overload degradation can engage) were
previously enforced only by convention.  This analyzer makes them
mergeable-or-not, the role TSAN/clang-tidy wiring plays for the reference
runtime's C++ core.

v2 grows the file-local checks into a package-wide analysis: every lint
run builds a module-resolved call graph plus per-function effect
summaries (attribute write-sets, lock-acquisition context, blocking-call
sets, collective-call sets) and propagates them transitively, so the
contracts the multi-lane RPC service (PR 6), the collective autotuner
(PR 8) and the RPC wire format rest on are *checked*, not prose:

  RTL001 no-blocking-under-lock   blocking calls inside ``with <lock>:``
  RTL002 thread-hygiene           Thread() must pass daemon= and name=
  RTL003 swallowed-exception      ``except Exception: pass`` must justify
  RTL004 metric-name-registry     ray_tpu_* names declared once + documented
  RTL005 async-blocking           no time.sleep / blocking get in async def
  RTL006 untimed-wait             Condition/Event.wait() & queue get need
                                  timeouts on runtime paths
  RTL007 lane-safety              a ``LANE_SAFE_METHODS`` handler — and
                                  everything it transitively calls — may
                                  mutate state only under a lock /
                                  ``shard_lock`` accessor, through the
                                  OwnerTable contract, or inside a
                                  ``ForwardToPrimary`` punt
  RTL008 spmd-lockstep            collective ops / tuner observe-commit
                                  calls must not sit under control flow
                                  conditioned on per-member state
                                  (rank, hostname, env, time, random)
  RTL009 rpc-wire-contract        string method names at ``.call``/
                                  ``.notify`` sites must resolve to a real
                                  ``handle_*`` on the matching service;
                                  ``LANE_SAFE_METHODS`` entries must name
                                  existing sync handlers; notify-only
                                  (oneway) handlers must not return values
  RTL010 async-blocking-transitive RTL005 through the call graph: a
                                  blocking call N frames below an async
                                  handler still stalls the event loop

Meta diagnostics (never waivable): RTL000 parse-error, RTL011
waiver-expired (a waiver whose ``expires`` date has passed is a lint
error, and it stops suppressing its site).

Usage::

    python -m ray_tpu.devtools.lint [paths...]
    python -m ray_tpu.devtools.lint --changed     # mtime+hash cache
    python -m ray_tpu.devtools.lint --json
    python -m ray_tpu.devtools.lint --list-rules

With no paths, lints the ``ray_tpu`` package this module was imported
from.  Exit status: 0 clean, 1 unwaived violations, 2 usage/parse error.

Waivers: a checked-in ``lint_waivers.toml`` next to this module
grandfathers specific sites (each entry carries a reason and date, and
optionally an ``expires = "YYYY-MM-DD"`` deadline), and an inline
``# raylint: waive[RTL00X] why`` comment on the flagged line waives one
site in place.  Unwaived violations fail the run; unused waiver entries
are reported so the file stays minimal.

Soundness notes (documented limits, see docs/lint.md): call edges into
``getattr``-style dynamic dispatch, nested ``def``/``lambda`` bodies and
unresolvable imports fall back to *unknown* and are not traversed;
RTL008 flags collectives lexically under a per-member condition, not
divergence via early return.  The dynamic companion
(``RAY_TPU_DEBUG_LANES=1``, ``ray_tpu/util/debug_lanes.py``) covers the
same lane contract from the runtime side.
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "RTL000": "parse-error",  # not waivable: an unparseable file is never OK
    "RTL001": "no-blocking-under-lock",
    "RTL002": "thread-hygiene",
    "RTL003": "swallowed-exception",
    "RTL004": "metric-name-registry",
    "RTL005": "async-blocking",
    "RTL006": "untimed-wait",
    "RTL007": "lane-safety",
    "RTL008": "spmd-lockstep",
    "RTL009": "rpc-wire-contract",
    "RTL010": "async-blocking-transitive",
    "RTL011": "waiver-expired",  # not waivable: meta-rule about the waiver file
}

UNWAIVABLE = frozenset({"RTL000", "RTL011"})

# Rules whose scope is "runtime paths": the concurrency-sensitive layers.
# Files outside a ray_tpu package (e.g. test fixture snippets) are treated
# as runtime scope so every rule is exercisable on a standalone file.
RUNTIME_SCOPE_PREFIXES = (
    "core/", "serve/", "util/", "dag/", "collective/", "autoscaler/",
)
RUNTIME_SCOPE_FILES = ("dashboard.py",)

_LOCK_NAME_RE = re.compile(
    r"(^|_)(lock|locks|cv|cond|condition|mutex)(_|$)|lock$", re.IGNORECASE
)
_QUEUE_NAME_RE = re.compile(
    r"(^|_)(q|queue|queues|chan|channel|inbox|mailbox)(_|$)|queue$",
    re.IGNORECASE,
)
_METRIC_NAME_RE = re.compile(r"ray_tpu_[a-z0-9_]+")
_WAIVE_COMMENT_RE = re.compile(
    r"#\s*raylint:\s*waive\[([A-Z0-9,\s]+)\]"
)
_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}$")

# --- interprocedural-rule knobs ------------------------------------------
# Modules that ARE the mutation contract: lane-side writes inside them are
# by-design (OwnerTable's per-shard locks / documented GIL-atomic
# telemetry), so RTL007 traversal stops at their boundary.
CONTRACT_MODULES = frozenset({"core.owner_table"})
# Attribute types whose mutating methods are the contract (per-shard locks
# live inside): `self.owned.pop(...)` is sanctioned, `self.owned[k] = v`
# still must hold shard_lock.
CONTRACT_TYPES = frozenset({"OwnerTable"})
# RPC-internal frame names that are not handler-dispatched methods.
PROTOCOL_METHODS = frozenset({"__hello__", "__goodbye__", "__batch__",
                              "R", "E"})
# Container-mutating method names treated as writes for RTL007.
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "update", "setdefault",
    "remove", "discard", "clear", "extend", "insert",
})
# Collective operations (SPMD lockstep contract, RTL008).
_COLLECTIVE_ATTRS = frozenset({
    "allreduce", "all_reduce", "allgather", "all_gather", "reducescatter",
    "reduce_scatter", "alltoall", "all_to_all", "psum", "pmean",
})
# Lockstep-sensitive tuner methods (selection depends ONLY on the
# per-bucket call sequence — a skipped observe() desynchronizes the
# replicated decision table).
_TUNER_METHODS = frozenset({"observe", "select", "commit", "_commit",
                            "force_reprobe", "select_for_group"})
_MEMBER_NAME_RE = re.compile(
    r"rank|host_?name|member|process_index|world_rank", re.IGNORECASE
)
_MEMBER_CALLS = frozenset({
    "os.getenv", "socket.gethostname", "platform.node",
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
})
_MEMBER_CALL_PREFIXES = ("random.", "uuid.", "secrets.")


class Violation:
    __slots__ = ("rule", "path", "line", "col", "message", "waived",
                 "waive_source")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.waived = False
        self.waive_source = ""

    def render(self) -> str:
        tag = f" [waived: {self.waive_source}]" if self.waived else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{RULES[self.rule]}: {self.message}{tag}")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "waived": self.waived, "waive_source": self.waive_source}

    @classmethod
    def from_dict(cls, d: dict) -> "Violation":
        v = cls(d["rule"], d["path"], d["line"], d["col"], d["message"])
        v.waived = bool(d.get("waived"))
        v.waive_source = d.get("waive_source", "")
        return v


# --------------------------------------------------------------- waivers
class WaiverError(Exception):
    pass


class Waiver:
    __slots__ = ("rules", "path", "contains", "line", "reason", "date",
                 "expires", "used", "srcline")

    def __init__(self, rules: Sequence[str], path: str,
                 contains: Optional[str], line: Optional[int],
                 reason: str, date: str, expires: Optional[str] = None,
                 srcline: int = 0):
        self.rules = tuple(rules)
        self.path = path.replace(os.sep, "/")
        self.contains = contains
        self.line = line
        self.reason = reason
        self.date = date
        self.expires = expires
        self.used = False
        self.srcline = srcline

    def expired(self, today: Optional[str] = None) -> bool:
        if self.expires is None:
            return False
        # ISO dates compare correctly as strings.
        return self.expires <= (today or time.strftime("%Y-%m-%d"))

    def matches(self, v: Violation, source_line: str) -> bool:
        if v.rule not in self.rules:
            return False
        # Suffix match with a path-component boundary: a waiver for
        # "core/rpc.py" must not also cover "score/rpc.py".
        vpath = v.path.replace(os.sep, "/")
        if vpath != self.path and not vpath.endswith("/" + self.path):
            return False
        if self.line is not None and self.line != v.line:
            return False
        if self.contains is not None and self.contains not in source_line:
            return False
        return True


def parse_waivers(path: str) -> List[Waiver]:
    """Parse the waiver file: a TOML subset (``[[waiver]]`` tables of
    string/int assignments) — parsed by hand because the runtime targets
    interpreters without ``tomllib`` and must not grow dependencies."""
    waivers: List[Waiver] = []
    current: Optional[dict] = None
    current_start = 0

    def finish(entry: Optional[dict], at_line: int):
        if entry is None:
            return
        missing = [k for k in ("rule", "path", "reason", "date")
                   if k not in entry]
        if missing:
            raise WaiverError(
                f"{path}: waiver ending at line {at_line} is missing "
                f"required field(s): {', '.join(missing)}"
            )
        rules = [r.strip() for r in entry["rule"].split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            raise WaiverError(
                f"{path}: waiver ending at line {at_line} names unknown "
                f"rule(s): {', '.join(unknown)}"
            )
        line_no = entry.get("line")
        if line_no is not None:
            line_no = int(line_no)
        expires = entry.get("expires")
        if expires is not None and not _DATE_RE.match(str(expires)):
            raise WaiverError(
                f"{path}: waiver ending at line {at_line} has malformed "
                f"expires date {expires!r} (want YYYY-MM-DD)"
            )
        waivers.append(Waiver(rules, entry["path"], entry.get("contains"),
                              line_no, entry["reason"], entry["date"],
                              expires, current_start))

    with open(path, encoding="utf-8") as f:
        i = 0
        for i, raw in enumerate(f, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[waiver]]":
                finish(current, i)
                current = {}
                current_start = i
                continue
            m = re.match(
                r'^([A-Za-z_]+)\s*=\s*(?:"((?:[^"\\]|\\.)*)"|(\d+))\s*'
                r"(?:#.*)?$", line,
            )
            if m is None or current is None:
                raise WaiverError(
                    f"{path}:{i}: unparseable waiver line: {line!r} "
                    "(expected [[waiver]] tables of key = \"string\" or "
                    "key = integer assignments)"
                )
            key, s_val, i_val = m.group(1), m.group(2), m.group(3)
            current[key] = (
                int(i_val) if i_val is not None
                else s_val.encode().decode("unicode_escape")
            )
        finish(current, i if waivers or current else 0)
    return waivers


# ------------------------------------------------------------ AST helpers
def _terminal_name(node: ast.AST) -> Optional[str]:
    """`self._tier_lock` -> "_tier_lock", `lock` -> "lock"."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """`time.sleep` -> "time.sleep"; gives up on non-trivial bases."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_lock_expr(node: ast.AST) -> bool:
    name = _terminal_name(node)
    if name is None:
        if isinstance(node, ast.Call):
            dn = _dotted(node.func) or ""
            last = dn.split(".")[-1]
            # threading.Lock() acquired inline, and lock-returning
            # accessors (`with self.owned.shard_lock(oid):` — the
            # OwnerTable lane-side mutation contract).
            return (last in ("Lock", "RLock", "Condition")
                    or bool(_LOCK_NAME_RE.search(last)))
        return False
    return bool(_LOCK_NAME_RE.search(name))


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _body_nodes_no_nested_defs(body: Sequence[ast.stmt]):
    """Yield every node in ``body`` without descending into nested
    function/class definitions (their execution escapes the lock/async
    context being analyzed)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _blocking_call_reason(node: ast.Call) -> Optional[str]:
    """If ``node`` is one of the calls RTL001/RTL005 forbid, say why."""
    dn = _dotted(node.func)
    if dn == "time.sleep":
        return "time.sleep() blocks the holder"
    if dn is not None and (dn.startswith("subprocess.")):
        return f"{dn}() forks/blocks on a child process"
    if dn in ("ray_tpu.get", "ray.get"):
        return f"{dn}() is a distributed blocking get"
    if isinstance(node.func, ast.Attribute) and node.func.attr == "result":
        return ".result() blocks on a future"
    return None


def _is_untimed_wait(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute) or _has_kw(node, "timeout"):
        return False
    if node.func.attr == "wait":
        return not node.args
    if node.func.attr == "wait_for":
        # Condition.wait_for(predicate) loops an untimed wait() inside;
        # asyncio.wait_for(aw, t) carries its timeout as 2nd positional.
        return len(node.args) <= 1
    return False


def _is_untimed_queue_get(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"):
        return False
    recv = _terminal_name(node.func.value)
    if recv is None or not _QUEUE_NAME_RE.search(recv):
        return False
    if _has_kw(node, "timeout"):
        return False
    # Non-blocking try-gets raise Empty immediately — bounded by nature.
    for kw in node.keywords:
        if (kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False):
            return False
    if (node.args and isinstance(node.args[0], ast.Constant)
            and node.args[0].value is False):
        return False
    # q.get() / q.get(True) / q.get(block=True) — all unbounded.
    positional_timeout = len(node.args) >= 2
    return not positional_timeout


def _member_cond_desc(test: ast.AST) -> Optional[str]:
    """If a control-flow test depends on per-member state (rank/hostname/
    env/time/random), describe the dependency; else None (RTL008)."""
    for node in ast.walk(test):
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = _terminal_name(node)
            if name == "environ":
                return "os.environ"
            if name and _MEMBER_NAME_RE.search(name):
                return name
        elif isinstance(node, ast.Call):
            dn = _dotted(node.func) or ""
            if dn in _MEMBER_CALLS:
                return f"{dn}()"
            if dn.startswith(_MEMBER_CALL_PREFIXES):
                return f"{dn}()"
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("gethostname", "getenv"):
                return f"{node.func.attr}()"
    return None


def _collective_desc(node: ast.Call) -> Optional[str]:
    """Name of the collective / tuner-lockstep operation, or None."""
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        recv = _terminal_name(node.func.value) or ""
        if attr in _COLLECTIVE_ATTRS:
            # pubsub/event "broadcast"-style fan-outs are not SPMD
            # collectives; only comm-group receivers count for ambiguous
            # names, while unambiguous op names always count.
            return f"{recv}.{attr}" if recv else attr
        if attr == "broadcast" and ("group" in recv or "comm" in recv
                                    or "mesh" in recv):
            return f"{recv}.{attr}"
        if attr in _TUNER_METHODS and "tuner" in recv.lower():
            return f"{recv}.{attr}"
        if attr == "select_for_group":
            return attr
    elif isinstance(node.func, ast.Name):
        if node.func.id in _COLLECTIVE_ATTRS \
                or node.func.id == "select_for_group":
            return node.func.id
    return None


# ------------------------------------------------------------- the checker
class FileChecker(ast.NodeVisitor):
    def __init__(self, path: str, source: str, runtime_scope: bool,
                 declared_metrics: Set[str], registry_file: bool):
        self.path = path
        self.source_lines = source.splitlines()
        self.runtime_scope = runtime_scope
        self.declared_metrics = declared_metrics
        self.registry_file = registry_file
        self.violations: List[Violation] = []
        self._awaited: Set[int] = set()
        self._async_depth = 0
        self._thread_ctors: Set[str] = {"threading.Thread", "Thread"}

    # -- plumbing ---------------------------------------------------------
    def check(self, tree: Optional[ast.AST] = None) -> List[Violation]:
        if tree is None:
            try:
                tree = ast.parse("\n".join(self.source_lines),
                                 filename=self.path)
            except SyntaxError as e:
                self._add("RTL000", e.lineno or 1, 0,
                          f"file does not parse: {e.msg}")
                return self.violations
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                # `import threading as _t` -> match `_t.Thread(...)` too.
                for alias in node.names:
                    if alias.name == "threading" and alias.asname:
                        self._thread_ctors.add(f"{alias.asname}.Thread")
            elif isinstance(node, ast.ImportFrom):
                # `from threading import Thread as Thr` -> match `Thr(...)`.
                if node.module == "threading":
                    for alias in node.names:
                        if alias.name == "Thread":
                            self._thread_ctors.add(
                                alias.asname or alias.name
                            )
            elif isinstance(node, ast.Await):
                self._awaited.add(id(node.value))
            elif isinstance(node, ast.Call):
                # `asyncio.wait_for(ev.wait(), timeout)` bounds the inner
                # wait — exempt its arguments from the untimed-wait rule.
                dn = _dotted(node.func) or ""
                if dn.split(".")[-1] == "wait_for":
                    for arg in node.args:
                        self._awaited.add(id(arg))
        self.visit(tree)
        return self.violations

    def _add(self, rule: str, line: int, col: int, message: str):
        self.violations.append(Violation(rule, self.path, line, col, message))

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1]
        return ""

    # -- RTL001 -----------------------------------------------------------
    def _check_with(self, node):
        if not self.runtime_scope:
            return
        lock_items = [
            item for item in node.items
            if _is_lock_expr(item.context_expr)
        ]
        if not lock_items:
            return
        lock_desc = _terminal_name(lock_items[0].context_expr) or "lock"
        for inner in _body_nodes_no_nested_defs(node.body):
            if not isinstance(inner, ast.Call):
                continue
            reason = _blocking_call_reason(inner)
            if reason is None and _is_untimed_wait(inner):
                reason = "untimed .wait() parks the thread with the lock" \
                         " context in scope"
            if reason is not None:
                self._add(
                    "RTL001", inner.lineno, inner.col_offset,
                    f"blocking call inside `with {lock_desc}:` — {reason}; "
                    "move it outside the critical section",
                )

    def visit_With(self, node: ast.With):
        self._check_with(node)
        self.generic_visit(node)

    # -- RTL002 -----------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        dn = _dotted(node.func) or ""
        if dn in self._thread_ctors:
            missing = [kw for kw in ("daemon", "name")
                       if not _has_kw(node, kw)]
            if missing:
                self._add(
                    "RTL002", node.lineno, node.col_offset,
                    "threading.Thread(...) must set "
                    f"{' and '.join(m + '=' for m in missing)} explicitly "
                    "(unnamed/implicit-daemon threads are undebuggable "
                    "and can block interpreter exit)",
                )
        # -- RTL005 / RTL006 (call-shaped rules) --------------------------
        if self.runtime_scope:
            if self._async_depth > 0:
                reason = _blocking_call_reason(node)
                if reason is not None and not dn.startswith("subprocess."):
                    # subprocess is RTL001's concern; async bodies care
                    # about anything that parks the event loop thread.
                    self._add(
                        "RTL005", node.lineno, node.col_offset,
                        f"blocking call in async def — {reason}; the event "
                        "loop (and every coroutine on it) stalls. Use the "
                        "async equivalent or run_in_executor",
                    )
            if id(node) not in self._awaited:
                if _is_untimed_wait(node):
                    self._add(
                        "RTL006", node.lineno, node.col_offset,
                        "untimed .wait(): a lost notify or wedged peer "
                        "hangs this thread forever — pass a timeout and "
                        "re-check the predicate",
                    )
                elif _is_untimed_queue_get(node):
                    self._add(
                        "RTL006", node.lineno, node.col_offset,
                        "unbounded queue get(): pass timeout= so overload "
                        "degrades into a timeout error instead of a hang",
                    )
        self.generic_visit(node)

    # -- RTL003 -----------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self.runtime_scope and self._is_broad_handler(node):
            body = [
                stmt for stmt in node.body
                if not (isinstance(stmt, ast.Expr)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str))
            ]
            if len(body) == 1 and isinstance(body[0], ast.Pass):
                self._add(
                    "RTL003", node.lineno, node.col_offset,
                    "broad except with a pass-only body swallows every "
                    "failure silently — log it, count it via "
                    "util/metrics.py, or waive with a justification",
                )
        self.generic_visit(node)

    @staticmethod
    def _is_broad_handler(node: ast.ExceptHandler) -> bool:
        if node.type is None:
            return True
        types = (node.type.elts if isinstance(node.type, ast.Tuple)
                 else [node.type])
        for t in types:
            name = _terminal_name(t)
            if name in ("Exception", "BaseException"):
                return True
        return False

    # -- RTL004 -----------------------------------------------------------
    def visit_Constant(self, node: ast.Constant):
        if (isinstance(node.value, str)
                and not self.registry_file
                and _METRIC_NAME_RE.fullmatch(node.value)
                and node.value not in self.declared_metrics):
            self._add(
                "RTL004", node.lineno, node.col_offset,
                f"metric name {node.value!r} is not declared in "
                "ray_tpu/util/metric_registry.py — declare it there (and "
                "document it in docs/observability.md), then import the "
                "constant",
            )
        self.generic_visit(node)

    # -- async tracking (RTL005) ------------------------------------------
    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._async_depth += 1
        self.generic_visit(node)
        self._async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef):
        # A sync def nested in an async def runs on its own thread/stack.
        saved, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = saved

    def visit_Lambda(self, node: ast.Lambda):
        # Same: a lambda handed to run_in_executor executes off-loop.
        saved, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = saved

    def visit_AsyncWith(self, node: ast.AsyncWith):
        # `async with lock:` is an asyncio lock — blocking calls under it
        # stall the loop, which RTL005 already reports per call site.
        self.generic_visit(node)


# =================================================================
# v2: per-function effect summaries + module-resolved call graph
# =================================================================
def _module_name(path: str) -> str:
    rel = _package_relative(path)
    if rel is None:
        return os.path.splitext(os.path.basename(path))[0]
    return os.path.splitext(rel)[0].replace("/", ".")


class _FunctionScanner:
    """Collects one function's effect summary: call edges, attribute
    write-sites (with lock / ForwardToPrimary context), blocking calls and
    collective calls — without descending into nested defs/lambdas, whose
    execution escapes the context being analyzed (a ``ForwardToPrimary``
    factory runs on the primary loop; a ``run_in_executor`` lambda runs
    off-loop)."""

    def __init__(self, module: str, cls: Optional[str], path: str,
                 call_sites: List[dict]):
        self.module = module
        self.cls = cls
        self.path = path
        self.call_sites = call_sites  # module-level RTL009 site list
        self.lock_depth = 0
        self.forward_depth = 0
        self.cond_stack: List[Optional[str]] = []
        self.aliases: Dict[str, str] = {}  # local name -> self attr it views

    def scan(self, node) -> dict:
        self.info = {
            "name": node.name,
            "cls": self.cls,
            "module": self.module,
            "path": self.path,
            "lineno": node.lineno,
            "is_async": isinstance(node, ast.AsyncFunctionDef),
            "calls": [],
            "writes": [],
            "blocking": [],
            "collectives": [],
            "value_returns": [],
            "dynamic_calls": 0,
        }
        for stmt in node.body:
            self._stmt(stmt)
        return self.info

    # -- context helpers --------------------------------------------------
    def _member_cond(self) -> Optional[str]:
        for cond in reversed(self.cond_stack):
            if cond is not None:
                return cond
        return None

    def _self_root(self, node) -> Optional[str]:
        """First attribute above ``self`` in an access chain, following
        one level of local aliasing (`job = self.jobs.get(..)` makes
        writes through `job` writes to `self.jobs`)."""
        cur = node
        for _ in range(32):
            if isinstance(cur, ast.Attribute):
                base = cur.value
                if isinstance(base, ast.Name):
                    if base.id == "self":
                        return cur.attr
                    return self.aliases.get(base.id)
                cur = base
            elif isinstance(cur, ast.Subscript):
                cur = cur.value
            elif isinstance(cur, ast.Call):
                f = cur.func
                # Only accessor methods return *views* into the shared
                # container; anything else (public_info(), copy(), ...)
                # hands back a fresh object mutating which is private.
                if isinstance(f, ast.Attribute) and f.attr in (
                        "get", "setdefault", "values", "items", "keys"):
                    cur = f.value
                else:
                    return None
            elif isinstance(cur, ast.Name):
                return self.aliases.get(cur.id)
            else:
                return None
        return None

    def _record_write(self, attr: str, desc: str, node,
                      mutator: Optional[str] = None):
        self.info["writes"].append({
            "attr": attr, "desc": desc,
            "lineno": node.lineno, "col": node.col_offset,
            "locked": self.lock_depth > 0,
            "in_forward": self.forward_depth > 0,
            "mutator": mutator,
        })

    def _write_target(self, tgt):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._write_target(e)
        elif isinstance(tgt, ast.Attribute):
            if isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                self._record_write(tgt.attr, f"self.{tgt.attr}", tgt)
            else:
                root = self._self_root(tgt.value)
                if root is not None:
                    self._record_write(
                        root, f"self.{root}…{_terminal_name(tgt) or ''}", tgt
                    )
        elif isinstance(tgt, ast.Subscript):
            root = self._self_root(tgt.value)
            if root is not None:
                self._record_write(root, f"self.{root}[…]", tgt)
        elif isinstance(tgt, ast.Name):
            root = self.aliases.get(tgt.id)
            # Plain rebinding of a local is not a write; only aug-assigns
            # route here (handled by caller).
        elif isinstance(tgt, ast.Starred):
            self._write_target(tgt.value)

    # -- statements -------------------------------------------------------
    def _stmt(self, node):
        t = type(node)
        if t in (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef):
            return  # nested definitions execute elsewhere
        if t is ast.Assign:
            self._expr(node.value)
            for tgt in node.targets:
                self._write_target(tgt)
            if len(node.targets) == 1 and isinstance(node.targets[0],
                                                     ast.Name):
                name = node.targets[0].id
                root = self._self_root(node.value)
                if root is not None:
                    self.aliases[name] = root
                else:
                    self.aliases.pop(name, None)
        elif t is ast.AugAssign:
            self._expr(node.value)
            tgt = node.target
            if isinstance(tgt, ast.Name):
                root = self.aliases.get(tgt.id)
                if root is not None:
                    self._record_write(root, f"self.{root} (via {tgt.id})",
                                       tgt)
            else:
                self._write_target(tgt)
        elif t is ast.AnnAssign:
            if node.value is not None:
                self._expr(node.value)
                self._write_target(node.target)
        elif t is ast.Delete:
            for tgt in node.targets:
                self._write_target(tgt)
        elif t is ast.Expr:
            self._expr(node.value)
        elif t is ast.Return:
            if node.value is not None:
                self._expr(node.value)
                if not (isinstance(node.value, ast.Constant)
                        and node.value.value is None):
                    self.info["value_returns"].append(node.lineno)
        elif t in (ast.If, ast.While):
            self._expr(node.test)
            self.cond_stack.append(_member_cond_desc(node.test))
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
            self.cond_stack.pop()
        elif t in (ast.For, ast.AsyncFor):
            self._expr(node.iter)
            if isinstance(node.target, ast.Name):
                root = self._self_root(node.iter)
                if root is not None:
                    self.aliases[node.target.id] = root
            for s in node.body:
                self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
        elif t in (ast.With, ast.AsyncWith):
            locked = False
            for item in node.items:
                self._expr(item.context_expr)
                if t is ast.With and _is_lock_expr(item.context_expr):
                    locked = True
                if item.optional_vars is not None and \
                        isinstance(item.optional_vars, ast.Name):
                    self.aliases.pop(item.optional_vars.id, None)
            if locked:
                self.lock_depth += 1
            for s in node.body:
                self._stmt(s)
            if locked:
                self.lock_depth -= 1
        elif t is ast.Try:
            for s in node.body:
                self._stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in node.orelse:
                self._stmt(s)
            for s in node.finalbody:
                self._stmt(s)
        else:
            # Raise / Assert / match / etc: walk children generically.
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self._stmt(child)
                elif isinstance(child, ast.expr):
                    self._expr(child)
                elif isinstance(child, (ast.match_case,)):
                    for s in child.body:
                        self._stmt(s)

    # -- expressions ------------------------------------------------------
    def _expr(self, node):
        if node is None:
            return
        t = type(node)
        if t is ast.Lambda:
            return  # executes elsewhere
        if t is ast.Call:
            self._call(node)
            return
        if t is ast.IfExp:
            self._expr(node.test)
            self.cond_stack.append(_member_cond_desc(node.test))
            self._expr(node.body)
            self._expr(node.orelse)
            self.cond_stack.pop()
            return
        if t is ast.BoolOp and len(node.values) > 1:
            # `rank == 0 and group.allreduce(x)`: later operands only
            # evaluate when the first holds.
            self._expr(node.values[0])
            self.cond_stack.append(_member_cond_desc(node.values[0]))
            for v in node.values[1:]:
                self._expr(v)
            self.cond_stack.pop()
            return
        if t in (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp):
            conds = 0
            for gen in node.generators:
                self._expr(gen.iter)
                for if_ in gen.ifs:
                    self._expr(if_)
                    self.cond_stack.append(_member_cond_desc(if_))
                    conds += 1
            if t is ast.DictComp:
                self._expr(node.key)
                self._expr(node.value)
            else:
                self._expr(node.elt)
            for _ in range(conds):
                self.cond_stack.pop()
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _call(self, node: ast.Call):
        f = node.func
        edge = None
        if isinstance(f, ast.Name):
            edge = ("bare", f.id)
        elif isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                edge = ("self", f.attr)
            else:
                dn = _dotted(f)
                if dn and dn.startswith("self.") and dn.count(".") == 2:
                    edge = ("attr", dn[5:])  # "owned.get"
                elif dn:
                    edge = ("dotted", dn)
                else:
                    # x[i].m(), getattr(h, n)(...), chained calls:
                    # dynamic dispatch falls back to unknown.
                    self.info["dynamic_calls"] += 1
        else:
            self.info["dynamic_calls"] += 1

        if edge is not None:
            self.info["calls"].append({
                "kind": edge[0], "name": edge[1],
                "lineno": node.lineno, "col": node.col_offset,
                "in_forward": self.forward_depth > 0,
                "member_cond": self._member_cond(),
            })

        reason = _blocking_call_reason(node)
        if reason is not None:
            self.info["blocking"].append({
                "reason": reason, "lineno": node.lineno,
                "col": node.col_offset,
                "in_forward": self.forward_depth > 0,
            })
        coll = _collective_desc(node)
        if coll is not None:
            self.info["collectives"].append({
                "name": coll, "lineno": node.lineno, "col": node.col_offset,
                "member_cond": self._member_cond(),
                "in_forward": self.forward_depth > 0,
            })

        if isinstance(f, ast.Attribute):
            # Container-mutating method on shared state (RTL007).
            if f.attr in _MUTATOR_METHODS:
                root = self._self_root(f.value)
                recv = _terminal_name(f.value) or ""
                if root is not None and not _QUEUE_NAME_RE.search(recv):
                    self._record_write(
                        root, f"self.{root}.{f.attr}(…)", node,
                        mutator=f.attr,
                    )
            # RPC wire call site (RTL009).
            if f.attr in ("call", "notify"):
                method = None
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    method = node.args[0].value
                self.call_sites.append({
                    "recv": self._recv_hint(f.value), "kind": f.attr,
                    "method": method, "lineno": node.lineno,
                    "col": node.col_offset,
                })

        # Descend: a ForwardToPrimary factory's contents run on the
        # primary loop, outside the lane contract being checked.
        is_forward = isinstance(f, ast.Name) and f.id == "ForwardToPrimary"
        if not is_forward and isinstance(f, ast.Attribute):
            is_forward = f.attr == "ForwardToPrimary"
        if is_forward:
            self.forward_depth += 1
        self._expr(f) if isinstance(f, ast.Attribute) and not \
            isinstance(f.value, ast.Name) else None
        for arg in node.args:
            self._expr(arg)
        for kw in node.keywords:
            self._expr(kw.value)
        if is_forward:
            self.forward_depth -= 1

    @staticmethod
    def _recv_hint(node) -> str:
        """Best-effort receiver name for an RPC call site: the deepest
        non-generic attribute in the chain (`self.worker_clients.get(a)`
        -> "worker_clients")."""
        tokens: List[str] = []
        cur = node
        for _ in range(16):
            if isinstance(cur, ast.Attribute):
                tokens.append(cur.attr)
                cur = cur.value
            elif isinstance(cur, ast.Name):
                tokens.append(cur.id)
                break
            elif isinstance(cur, ast.Call):
                cur = (cur.func.value if isinstance(cur.func, ast.Attribute)
                       else cur.func)
            elif isinstance(cur, ast.Subscript):
                cur = cur.value
            else:
                break
        for tok in tokens:
            if tok not in ("get", "self", "cls"):
                return tok
        return tokens[0] if tokens else ""


def _literal_strings(node) -> Optional[List[str]]:
    """String entries of a frozenset({...}) / {...} / (...) literal."""
    if isinstance(node, ast.Call) and _terminal_name(node.func) in (
            "frozenset", "set", "tuple", "list"):
        if not node.args:
            return []
        node = node.args[0]
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


def summarize_module(tree: ast.AST, path: str, runtime_scope: bool) -> dict:
    """Extract the per-module summary the interprocedural rules run on.
    Pure data (JSON-serializable) so ``--changed`` can cache it."""
    module = _module_name(path)
    summary = {
        "path": path, "module": module, "runtime_scope": runtime_scope,
        "imports": {}, "classes": {}, "functions": [], "call_sites": [],
    }
    pkg_parts = module.split(".")[:-1]

    def add_import_module(local: str, dotted: str):
        if dotted.startswith("ray_tpu."):
            dotted = dotted[len("ray_tpu."):]
        summary["imports"][local] = [dotted, None]

    def add_import_symbol(local: str, mod: str, symbol: str):
        if mod.startswith("ray_tpu."):
            mod = mod[len("ray_tpu."):]
        summary["imports"][local] = [mod, symbol]

    for node in tree.body if hasattr(tree, "body") else []:
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                add_import_module(local, alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                    if node.level > 1 else list(pkg_parts)
                mod = ".".join(base + (node.module.split(".")
                                       if node.module else []))
            else:
                mod = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                add_import_symbol(alias.asname or alias.name, mod,
                                  alias.name)

    def scan_function(fn, cls_name):
        scanner = _FunctionScanner(module, cls_name, path,
                                   summary["call_sites"])
        summary["functions"].append(scanner.scan(fn))

    for node in getattr(tree, "body", []):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_function(node, None)
        elif isinstance(node, ast.ClassDef):
            cls = {
                "lineno": node.lineno,
                "bases": [b for b in (_terminal_name(x) for x in node.bases)
                          if b],
                "lane_safe": None, "lane_safe_line": node.lineno,
                "attr_types": {}, "methods": [],
            }
            summary["classes"][node.name] = cls
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cls["methods"].append(item.name)
                    scan_function(item, node.name)
                    # `self.x = ClassName(...)` / `self.x: T = ...` type
                    # hints feed attr-receiver call resolution.
                    for sub in ast.walk(item):
                        if isinstance(sub, ast.Assign) and \
                                len(sub.targets) == 1 and \
                                isinstance(sub.targets[0], ast.Attribute) \
                                and isinstance(sub.targets[0].value,
                                               ast.Name) \
                                and sub.targets[0].value.id == "self" \
                                and isinstance(sub.value, ast.Call):
                            tname = _terminal_name(sub.value.func)
                            if tname and tname[:1].isupper():
                                cls["attr_types"].setdefault(
                                    sub.targets[0].attr, tname)
                        elif isinstance(sub, ast.AnnAssign) and \
                                isinstance(sub.target, ast.Attribute) and \
                                isinstance(sub.target.value, ast.Name) and \
                                sub.target.value.id == "self":
                            tname = _terminal_name(sub.annotation)
                            if tname and tname[:1].isupper():
                                cls["attr_types"].setdefault(
                                    sub.target.attr, tname)
                elif isinstance(item, (ast.Assign, ast.AnnAssign)):
                    tgt = (item.targets[0] if isinstance(item, ast.Assign)
                           else item.target)
                    if isinstance(tgt, ast.Name) and \
                            tgt.id == "LANE_SAFE_METHODS" and \
                            item.value is not None:
                        entries = _literal_strings(item.value)
                        if entries is not None:
                            cls["lane_safe"] = entries
                            cls["lane_safe_line"] = item.lineno
    return summary


class _Program:
    """Whole-batch index over module summaries: function lookup, class
    hierarchy walk, call-edge resolution."""

    def __init__(self, summaries: Sequence[dict]):
        self.summaries = list(summaries)
        self.modsum: Dict[str, dict] = {}
        self.by_key: Dict[Tuple[str, Optional[str], str], dict] = {}
        self.classes: Dict[Tuple[str, str], dict] = {}
        self.class_sites: Dict[str, List[Tuple[str, dict]]] = {}
        self.call_sites: List[dict] = []
        for s in self.summaries:
            self.modsum[s["module"]] = s
            for f in s["functions"]:
                self.by_key[(s["module"], f["cls"], f["name"])] = f
            for cname, c in s["classes"].items():
                self.classes[(s["module"], cname)] = c
                self.class_sites.setdefault(cname, []).append(
                    (s["module"], c))
            for site in s["call_sites"]:
                site = dict(site)
                site["path"] = s["path"]
                self.call_sites.append(site)
        self._resolve_memo: Dict[tuple, Optional[tuple]] = {}
        self._handler_memo: Dict[Tuple[str, str], Set[str]] = {}

    # -- class/method resolution ------------------------------------------
    def _find_class(self, module: str, name: str) -> Optional[Tuple[str, dict]]:
        c = self.classes.get((module, name))
        if c is not None:
            return module, c
        s = self.modsum.get(module)
        if s is not None:
            imp = s["imports"].get(name)
            if imp is not None and imp[1] is not None:
                c = self.classes.get((imp[0], imp[1]))
                if c is not None:
                    return imp[0], c
        sites = self.class_sites.get(name)
        if sites and len(sites) == 1:
            return sites[0]
        return None

    def resolve_method(self, module: str, cls: str, name: str,
                       _depth: int = 0) -> Optional[tuple]:
        if _depth > 8:
            return None
        found = self._find_class(module, cls)
        if found is None:
            return None
        cmod, cdict = found
        if name in cdict["methods"]:
            return (cmod, cls, name)
        for base in cdict["bases"]:
            r = self.resolve_method(cmod, base, name, _depth + 1)
            if r is not None:
                return r
        return None

    def attr_type(self, module: str, cls: Optional[str],
                  attr: str) -> Optional[str]:
        seen = set()
        while cls is not None and cls not in seen:
            seen.add(cls)
            found = self._find_class(module, cls)
            if found is None:
                return None
            module, cdict = found
            t = cdict["attr_types"].get(attr)
            if t is not None:
                return t
            cls = cdict["bases"][0] if cdict["bases"] else None
        return None

    def class_handlers(self, module: str, cls: str) -> Set[str]:
        """handle_* method names (sans prefix) on a class incl. bases."""
        memo = self._handler_memo.get((module, cls))
        if memo is not None:
            return memo
        out: Set[str] = set()
        self._handler_memo[(module, cls)] = out  # cycle guard
        found = self._find_class(module, cls)
        if found is not None:
            cmod, cdict = found
            for m in cdict["methods"]:
                if m.startswith("handle_"):
                    out.add(m[len("handle_"):])
            for base in cdict["bases"]:
                out |= self.class_handlers(cmod, base)
        return out

    # -- call-edge resolution ---------------------------------------------
    def resolve(self, finfo: dict, edge: dict) -> Optional[tuple]:
        key = (finfo["module"], finfo["cls"], edge["kind"], edge["name"])
        if key in self._resolve_memo:
            return self._resolve_memo[key]
        r = self._resolve_uncached(finfo, edge)
        self._resolve_memo[key] = r
        return r

    def _resolve_uncached(self, finfo, edge) -> Optional[tuple]:
        mod = finfo["module"]
        kind, name = edge["kind"], edge["name"]
        s = self.modsum.get(mod, {})
        imports = s.get("imports", {})
        if kind == "self":
            if finfo["cls"] is None:
                return None
            return self.resolve_method(mod, finfo["cls"], name)
        if kind == "bare":
            if (mod, None, name) in self.by_key:
                return (mod, None, name)
            imp = imports.get(name)
            if imp is not None and imp[1] is not None \
                    and (imp[0], None, imp[1]) in self.by_key:
                return (imp[0], None, imp[1])
            return None
        if kind == "attr":
            attr, meth = name.split(".", 1)
            t = self.attr_type(mod, finfo["cls"], attr)
            if t is None:
                return None
            found = self._find_class(mod, t)
            if found is None:
                return None
            return self.resolve_method(found[0], t, meth)
        if kind == "dotted":
            parts = name.split(".")
            imp = imports.get(parts[0])
            if imp is None:
                return None
            m2, sym = imp
            if sym is None:
                # `import x.y as z; z.f(...)`
                if len(parts) == 2 and (m2, None, parts[1]) in self.by_key:
                    return (m2, None, parts[1])
                return None
            # `from m import sub; sub.f(...)` — sub is a module or class.
            cand_mod = f"{m2}.{sym}" if m2 else sym
            if len(parts) == 2:
                if (cand_mod, None, parts[1]) in self.by_key:
                    return (cand_mod, None, parts[1])
                return self.resolve_method(m2, sym, parts[1])
        return None

    def module_of(self, key: tuple) -> str:
        return key[0]

    def finfo(self, key: tuple) -> dict:
        return self.by_key[key]


def _short(key: tuple) -> str:
    mod, cls, name = key
    return f"{cls}.{name}" if cls else name


def _service_group(prog: _Program, hint: str) -> Optional[List[Tuple[str, str]]]:
    """Map an RPC call-site receiver hint to the (module, class) service
    group it addresses; None means unknown (check against the union)."""
    h = (hint or "").lower()
    if "cp" in h or "control" in h:
        pat = "controlplane"
    elif "agent" in h:
        pat = "agent"
    elif "worker" in h or "owner" in h or "caller" in h:
        pat = "worker"
    else:
        return None
    out = [
        (mod, cname) for (mod, cname), c in prog.classes.items()
        if pat in cname.lower()
        and any(m.startswith("handle_") for m in c["methods"])
    ]
    return out or None


# -------------------------------------------------- interprocedural rules
def _rtl007(prog: _Program) -> List[Violation]:
    findings: Dict[tuple, tuple] = {}
    for (mod, cname), cdict in sorted(prog.classes.items()):
        entries = cdict.get("lane_safe")
        if not entries:
            continue
        for entry in sorted(entries):
            hkey = prog.resolve_method(mod, cname, "handle_" + entry)
            if hkey is None:
                continue  # RTL009 reports the missing handler
            seen: Set[tuple] = set()
            stack = [(hkey, (f"handle_{entry}",))]
            while stack:
                key, chain = stack.pop()
                if key in seen:
                    continue
                seen.add(key)
                fi = prog.finfo(key)
                for w in fi["writes"]:
                    if w["locked"] or w["in_forward"]:
                        continue
                    if w["mutator"] is not None and prog.attr_type(
                            fi["module"], fi["cls"], w["attr"]
                    ) in CONTRACT_TYPES:
                        continue
                    fkey = (fi["path"], w["lineno"], w["attr"])
                    findings.setdefault(
                        fkey, (cname, entry, chain, w, fi))
                for e in fi["calls"]:
                    if e["in_forward"] or len(chain) > 12:
                        continue
                    ck = prog.resolve(fi, e)
                    if ck is None:
                        continue
                    if prog.module_of(ck) in CONTRACT_MODULES:
                        continue
                    cfi = prog.finfo(ck)
                    if cfi["cls"] in CONTRACT_TYPES:
                        continue
                    stack.append((ck, chain + (_short(ck),)))
    out = []
    for (path, lineno, attr), (cname, entry, chain, w, fi) in \
            sorted(findings.items()):
        via = " -> ".join(chain)
        out.append(Violation(
            "RTL007", path, lineno, w["col"],
            f"lane-safe method {entry!r} ({cname}) reaches a mutation of "
            f"{w['desc']} outside the shard-lock contract [{via}] — lane "
            "threads may race the primary loop here; hold a lock/"
            "shard_lock, punt via ForwardToPrimary, or waive with a "
            "justification",
        ))
    return out


def _collective_reps(prog: _Program) -> Dict[tuple, tuple]:
    rep: Dict[tuple, tuple] = {}
    for key, f in prog.by_key.items():
        for c in f["collectives"]:
            if not c["in_forward"]:
                rep[key] = (c["name"], ())
                break
    changed = True
    while changed:
        changed = False
        for key, f in prog.by_key.items():
            if key in rep:
                continue
            for e in f["calls"]:
                if e["in_forward"]:
                    continue
                ck = prog.resolve(f, e)
                if ck is not None and ck in rep:
                    name, chain = rep[ck]
                    rep[key] = (name, (_short(ck),) + chain)
                    changed = True
                    break
    return rep


def _rtl008(prog: _Program) -> List[Violation]:
    rep = _collective_reps(prog)
    out, seen = [], set()

    def add(path, lineno, col, msg):
        if (path, lineno) in seen:
            return
        seen.add((path, lineno))
        out.append(Violation("RTL008", path, lineno, col, msg))

    for key, f in sorted(prog.by_key.items(),
                         key=lambda kv: (kv[0][0], kv[0][1] or "",
                                         kv[0][2])):
        if not prog.modsum.get(f["module"], {}).get("runtime_scope", True):
            continue
        for c in f["collectives"]:
            if c["member_cond"] and not c["in_forward"]:
                add(f["path"], c["lineno"], c["col"],
                    f"collective/tuner call {c['name']}() under control "
                    f"flow conditioned on per-member state "
                    f"({c['member_cond']}) — members that branch "
                    "differently desynchronize the SPMD call sequence "
                    "(tuner decision tables replicate by call order)")
        for e in f["calls"]:
            if not e["member_cond"] or e["in_forward"]:
                continue
            ck = prog.resolve(f, e)
            if ck is None or ck not in rep:
                continue
            name, chain = rep[ck]
            via = " -> ".join((_short(ck),) + chain)
            add(f["path"], e["lineno"], e["col"],
                f"call under per-member condition ({e['member_cond']}) "
                f"transitively performs collective/tuner op {name}() "
                f"[{via}] — SPMD lockstep divergence risk")
    return out


def _rtl009(prog: _Program) -> List[Violation]:
    out: List[Violation] = []
    handler_classes = [
        (mod, cname) for (mod, cname), c in sorted(prog.classes.items())
        if any(m.startswith("handle_") for m in c["methods"])
    ]
    all_known: Set[str] = set()
    for mod, cname in handler_classes:
        all_known |= prog.class_handlers(mod, cname)

    # (a) every literal method string at a call/notify site must resolve
    # to a real handler on the matching service class.
    if all_known:
        for site in prog.call_sites:
            m = site["method"]
            if m is None or m in PROTOCOL_METHODS:
                continue
            group = _service_group(prog, site["recv"])
            if group:
                known = set()
                for gmod, gcls in group:
                    known |= prog.class_handlers(gmod, gcls)
                desc = "/".join(sorted({c for _, c in group}))
            else:
                known, desc = all_known, "any known service"
            if m not in known:
                out.append(Violation(
                    "RTL009", site["path"], site["lineno"], site["col"],
                    f".{site['kind']}({m!r}, …): no handler 'handle_{m}' "
                    f"on {desc} — the server will answer with an RpcError "
                    "(or silently drop the oneway frame); stale string "
                    "method name?",
                ))

    # (b) LANE_SAFE_METHODS entries must name existing *sync* handlers.
    for (mod, cname), cdict in sorted(prog.classes.items()):
        entries = cdict.get("lane_safe")
        if not entries:
            continue
        spath = prog.modsum[mod]["path"]
        for entry in sorted(entries):
            hkey = prog.resolve_method(mod, cname, "handle_" + entry)
            if hkey is None:
                out.append(Violation(
                    "RTL009", spath, cdict["lane_safe_line"], 0,
                    f"LANE_SAFE_METHODS entry {entry!r} ({cname}) names no "
                    f"existing handler 'handle_{entry}' — lane dispatch "
                    "will forward every such call (or error)",
                ))
            elif prog.finfo(hkey)["is_async"]:
                out.append(Violation(
                    "RTL009", spath, cdict["lane_safe_line"], 0,
                    f"LANE_SAFE_METHODS entry {entry!r} ({cname}): "
                    f"'handle_{entry}' is async — lane dispatch requires a "
                    "sync handler, so this entry silently never runs on a "
                    "lane",
                ))

    # (c) notify-only (oneway) methods must not return values: msg_id 0
    # frames get no reply, so the return is dead code that reads like a
    # meaningful acknowledgement.
    notified = {s["method"] for s in prog.call_sites
                if s["kind"] == "notify" and s["method"]}
    called = {s["method"] for s in prog.call_sites
              if s["kind"] == "call" and s["method"]}
    for m in sorted(notified - called):
        for (mod, cname), cdict in sorted(prog.classes.items()):
            if "handle_" + m not in cdict["methods"]:
                continue
            fi = prog.by_key.get((mod, cname, "handle_" + m))
            if fi is None:
                continue
            for lineno in fi["value_returns"]:
                out.append(Violation(
                    "RTL009", fi["path"], lineno, 0,
                    f"'handle_{m}' ({cname}) returns a value, but "
                    f"{m!r} is only ever sent oneway (.notify) — the "
                    "value is silently dropped; use a bare return (or "
                    "promote the client sites to .call)",
                ))
    return out


def _blocking_reps(prog: _Program) -> Dict[tuple, tuple]:
    rep: Dict[tuple, tuple] = {}
    for key, f in prog.by_key.items():
        if f["is_async"]:
            continue
        for b in f["blocking"]:
            if b["reason"].startswith("subprocess"):
                continue  # RTL001's concern, mirrors RTL005's carve-out
            rep[key] = (b["reason"], ())
            break
    changed = True
    while changed:
        changed = False
        for key, f in prog.by_key.items():
            if f["is_async"] or key in rep:
                continue
            for e in f["calls"]:
                if _nonblocking_by_convention(e["name"]):
                    continue
                ck = prog.resolve(f, e)
                if ck is not None and ck in rep \
                        and not prog.finfo(ck)["is_async"]:
                    reason, chain = rep[ck]
                    rep[key] = (reason, (_short(ck),) + chain)
                    changed = True
                    break
    return rep


def _nonblocking_by_convention(edge_name: str) -> bool:
    """`*_nowait` variants gate their blocking branch on block=False
    internally; the path-insensitive propagation would otherwise drag
    their callers into the blocking set."""
    return edge_name.split(".")[-1].endswith("_nowait")


def _rtl010(prog: _Program) -> List[Violation]:
    rep = _blocking_reps(prog)
    out, seen = [], set()
    for key, f in sorted(prog.by_key.items(),
                         key=lambda kv: (kv[0][0], kv[0][1] or "",
                                         kv[0][2])):
        if not f["is_async"]:
            continue
        for e in f["calls"]:
            if e["in_forward"] or _nonblocking_by_convention(e["name"]):
                continue
            ck = prog.resolve(f, e)
            if ck is None or ck not in rep or prog.finfo(ck)["is_async"]:
                continue
            if (f["path"], e["lineno"]) in seen:
                continue
            seen.add((f["path"], e["lineno"]))
            reason, chain = rep[ck]
            via = " -> ".join((_short(ck),) + chain)
            out.append(Violation(
                "RTL010", f["path"], e["lineno"], e["col"],
                f"async def {_short(key)} calls into a sync path that "
                f"blocks [{via}: {reason}] — the event loop stalls "
                "exactly as if the blocking call were inline (RTL005 "
                "through the call graph); use the async equivalent or "
                "run_in_executor",
            ))
    return out


def run_global_rules(summaries: Sequence[dict]) -> List[Violation]:
    prog = _Program(summaries)
    out: List[Violation] = []
    out.extend(_rtl007(prog))
    out.extend(_rtl008(prog))
    out.extend(_rtl009(prog))
    out.extend(_rtl010(prog))
    return out


# ---------------------------------------------------------- file discovery
def _iter_python_files(paths: Sequence[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            raise FileNotFoundError(p)


def _package_relative(path: str) -> Optional[str]:
    """Path inside the ray_tpu package ('core/foo.py'), or None if the
    file is not under a ray_tpu directory."""
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "ray_tpu" not in parts:
        return None
    idx = len(parts) - 1 - parts[::-1].index("ray_tpu")
    rel = "/".join(parts[idx + 1:])
    return rel or None


def _in_runtime_scope(path: str) -> bool:
    rel = _package_relative(path)
    if rel is None:
        return True  # standalone snippet (fixtures): all rules apply
    return (rel.startswith(RUNTIME_SCOPE_PREFIXES)
            or rel in RUNTIME_SCOPE_FILES)


def _registry_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "util", "metric_registry.py")


def load_declared_metrics(registry_path: Optional[str] = None) -> Set[str]:
    """Metric names declared in the registry module — parsed from its AST
    so linting never imports runtime code."""
    registry_path = registry_path or _registry_path()
    declared: Set[str] = set()
    try:
        with open(registry_path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=registry_path)
    except (OSError, SyntaxError):
        return declared
    for node in ast.walk(tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and _METRIC_NAME_RE.fullmatch(node.value)):
            declared.add(node.value)
    return declared


def check_docs_coverage(declared: Set[str],
                        doc_path: Optional[str] = None) -> List[Violation]:
    """RTL004 second half: every registered name must appear in
    docs/observability.md (skipped silently when the docs tree is not
    present, e.g. an installed wheel)."""
    registry = _registry_path()
    if doc_path is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        doc_path = os.path.join(repo_root, "docs", "observability.md")
    if not os.path.isfile(doc_path):
        return []
    with open(doc_path, encoding="utf-8") as f:
        doc_text = f.read()
    out = []
    for name in sorted(declared):
        if name not in doc_text:
            out.append(Violation(
                "RTL004", registry, 1, 0,
                f"metric {name!r} is registered but undocumented — add it "
                f"to {os.path.relpath(doc_path)}",
            ))
    return out


# -------------------------------------------------------- incremental cache
CACHE_VERSION = 2


def default_cache_file() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".lint_cache.json")


def _load_cache(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            cache = json.load(f)
        if cache.get("version") == CACHE_VERSION:
            return cache
    except (OSError, ValueError):
        pass
    return {"version": CACHE_VERSION, "files": {}}


def _save_cache(path: str, cache: dict):
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cache, f, separators=(",", ":"))
        os.replace(tmp, path)
    except OSError:
        pass  # a cache that can't persist just means a cold next run


def _cache_entry_fresh(entry: dict, path: str) -> bool:
    """mtime+size first (cheap), content hash as the tiebreaker — a
    touch without an edit re-hashes but does not re-analyze."""
    try:
        st = os.stat(path)
    except OSError:
        return False
    key = entry.get("key") or [None, None, None]
    if key[0] == st.st_mtime_ns and key[1] == st.st_size:
        return True
    if key[1] != st.st_size:
        return False
    sha = _file_sha(path)
    if sha == key[2]:
        entry["key"] = [st.st_mtime_ns, st.st_size, sha]
        return True
    return False


def _file_sha(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _cache_key(path: str, data: bytes) -> list:
    st = os.stat(path)
    return [st.st_mtime_ns, st.st_size, hashlib.sha256(data).hexdigest()]


# ----------------------------------------------------------------- driver
def _inline_waive_rules(line_text: str) -> Set[str]:
    m = _WAIVE_COMMENT_RE.search(line_text)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


class _SourceLines:
    """Lazy source-line access for waiver matching: files analyzed this
    run are already in memory; cached files load on first need."""

    def __init__(self):
        self._lines: Dict[str, List[str]] = {}

    def put(self, path: str, lines: List[str]):
        self._lines[path] = lines

    def line(self, path: str, lineno: int) -> str:
        lines = self._lines.get(path)
        if lines is None:
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = []
            self._lines[path] = lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1]
        return ""


def run(paths: Sequence[str], waiver_file: Optional[str],
        check_docs: bool = True, changed_only: bool = False,
        cache_file: Optional[str] = None
        ) -> Tuple[List[Violation], List[Waiver]]:
    declared = load_declared_metrics()
    registry = _registry_path()
    waivers = parse_waivers(waiver_file) if waiver_file else []
    violations: List[Violation] = []
    summaries: List[dict] = []
    sources = _SourceLines()

    cache = None
    if changed_only:
        cache_file = cache_file or default_cache_file()
        cache = _load_cache(cache_file)

    for path in _iter_python_files(paths):
        apath = os.path.abspath(path)
        if cache is not None:
            entry = cache["files"].get(apath)
            if entry is not None and _cache_entry_fresh(entry, apath):
                violations.extend(
                    Violation.from_dict(d) for d in entry["violations"])
                if entry.get("summary") is not None:
                    summaries.append(entry["summary"])
                continue
        with open(path, "rb") as f:
            data = f.read()
        source = data.decode("utf-8")
        sources.put(path, source.splitlines())
        runtime_scope = _in_runtime_scope(path)
        checker = FileChecker(
            path, source, runtime_scope, declared,
            registry_file=apath == registry,
        )
        summary = None
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            checker._add("RTL000", e.lineno or 1, 0,
                         f"file does not parse: {e.msg}")
            local = checker.violations
        else:
            local = checker.check(tree)
            summary = summarize_module(tree, path, runtime_scope)
            summaries.append(summary)
        violations.extend(local)
        if cache is not None:
            cache["files"][apath] = {
                "key": _cache_key(apath, data),
                "violations": [v.to_dict() for v in local],
                "summary": summary,
            }

    violations.extend(run_global_rules(summaries))

    if check_docs:
        violations.extend(check_docs_coverage(declared))

    # Expired waivers are lint errors AND stop suppressing their sites.
    today = time.strftime("%Y-%m-%d")
    live_waivers = []
    for w in waivers:
        if w.expired(today):
            w.used = True  # don't double-report as unused
            violations.append(Violation(
                "RTL011", waiver_file or "<waivers>", w.srcline, 0,
                f"waiver ({','.join(w.rules)} {w.path}) expired on "
                f"{w.expires} — re-justify with a new expiry or fix the "
                "site (its violations resurface below)",
            ))
        else:
            live_waivers.append(w)

    for v in violations:
        if v.rule in UNWAIVABLE:
            continue  # parse failures / expired waivers are never waivable
        line_text = sources.line(v.path, v.line)
        if v.rule in _inline_waive_rules(line_text):
            v.waived = True
            v.waive_source = "inline comment"
            continue
        for w in live_waivers:
            if w.matches(v, line_text):
                v.waived = True
                v.waive_source = f"waiver file ({w.date}: {w.reason})"
                w.used = True
                break

    if cache is not None and cache_file:
        _save_cache(cache_file, cache)
    return violations, waivers


def default_waiver_file() -> Optional[str]:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_waivers.toml")
    return path if os.path.isfile(path) else None


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="raylint: runtime-invariant static analysis "
                    "(RTL001-RTL010)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "ray_tpu package)")
    parser.add_argument("--waivers", default=None,
                        help="waiver file (default: lint_waivers.toml "
                             "next to this module)")
    parser.add_argument("--no-waivers", action="store_true",
                        help="ignore the waiver file (show everything)")
    parser.add_argument("--no-docs-check", action="store_true",
                        help="skip the RTL004 docs-coverage pass")
    parser.add_argument("--changed", action="store_true",
                        help="incremental mode: reuse per-file results "
                             "from the mtime+hash cache, re-analyzing "
                             "only files whose content changed "
                             "(interprocedural rules always re-run over "
                             "all cached summaries)")
    parser.add_argument("--cache", default=None,
                        help="cache file for --changed (default: "
                             ".lint_cache.json next to this module)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit violations as JSON on stdout")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--show-waived", action="store_true",
                        help="also print waived violations")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, slug in RULES.items():
            print(f"{rule_id}  {slug}")
        return 0

    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    waiver_file = None if args.no_waivers else (
        args.waivers or default_waiver_file()
    )
    try:
        violations, waivers = run(
            paths, waiver_file, check_docs=not args.no_docs_check,
            changed_only=args.changed, cache_file=args.cache,
        )
    except (WaiverError, FileNotFoundError) as e:
        print(f"raylint: error: {e}", file=sys.stderr)
        return 2

    unwaived = [v for v in violations if not v.waived]
    shown = violations if (args.show_waived or args.as_json) else unwaived
    shown = sorted(shown, key=lambda v: (v.path, v.line, v.rule))
    n_waived = sum(1 for v in violations if v.waived)
    if args.as_json:
        print(json.dumps({
            "violations": [v.to_dict() for v in shown],
            "unwaived": len(unwaived),
            "waived": n_waived,
        }, indent=2))
    else:
        for v in shown:
            print(v.render())
    # Unused-waiver nagging only makes sense for a whole-package run — a
    # subset lint legitimately never exercises most entries.
    if not args.paths:
        for w in waivers:
            if not w.used:
                print(f"raylint: warning: unused waiver "
                      f"({','.join(w.rules)} {w.path}) — remove it",
                      file=sys.stderr)
    if not args.as_json:
        print(f"raylint: {len(unwaived)} violation(s), {n_waived} waived",
              file=sys.stderr)
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main())
