"""Fault-injection harness: composable, scoped, revertible injectors.

Chaos tests for the self-healing loop (``util/remediation.py``) need
faults that are *real enough* to drive the actual detect → act →
recover arc, yet hermetic: every injector is scoped (it targets one
component instance), revertible (``revert()`` restores the world, and
the injectors are context managers so test teardown cannot leak chaos),
and composable (``scoped(...)`` stacks several).

Injectors:

  - ``SlowPipelineStage`` — a slow host under one pipeline stage actor:
    ``compute_delay_s`` slows its forward ops (peers accumulate stall —
    the signature the straggler rule flags), ``recv_delay_s`` slows its
    tensor delivery.  The chaos state lives ON the actor, so the
    remediation respawn-and-replace clears it the way replacing a sick
    process clears its sickness.
  - ``KilledStageActor`` — kills a stage actor outright (one-shot);
    repeated kills drive the restart-storm → quarantine path.
  - ``OverloadedServeReplica`` — a closed-loop client fleet hammering a
    deployment until reverted; the fault is offered load exceeding one
    replica's capacity, and recovery is the remediation scale-up
    absorbing it (no revert needed for the SLO to clean up).
  - ``ThrottledCollectiveLink`` — degrades one fabric member's
    bandwidth for one algorithm (the slow-link model), driving the
    bandwidth-drift rule; the remediation re-probe lets the tuner
    re-commit around the throttled path.
  - ``KilledLeader`` — SIGKILLs the control-plane leader of an HA head;
    recovery is the warm standby taking the lease and clients
    re-anchoring (docs/ha.md), ``revert()`` respawns the standby.
  - ``ProviderCreateErrors`` / ``SlowProvisioning`` / ``NodeChurn`` —
    cloud-provider faults on a ``FakeMultiNodeProvider`` (create calls
    refused, VMs stuck in PROVISIONING, VMs crashing behind the API's
    back); recovery is the autoscaler's backoff, double-launch
    protection, and zombie-reclaim pass (docs/elastic.md).

``CollectiveFabricMember`` is the workload half of the collective
scenario: a simulated fabric (timed memcpy at per-algorithm bandwidths)
driven through the REAL tuner / flight-recorder / SLO pipeline — the
chaos boundary is the fabric model, everything above it is production
code.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


class ChaosInjector:
    """Base: ``apply()`` injects, ``revert()`` restores; context-manager
    use makes tests hermetic by construction."""

    def apply(self) -> "ChaosInjector":
        raise NotImplementedError

    def revert(self) -> None:
        raise NotImplementedError

    def __enter__(self) -> "ChaosInjector":
        return self.apply()

    def __exit__(self, *exc) -> None:
        self.revert()


class scoped:
    """Compose several injectors into one scope: applied in order,
    reverted in reverse, every revert attempted even if one fails."""

    def __init__(self, *injectors: ChaosInjector):
        self.injectors = injectors

    def __enter__(self) -> tuple:
        applied = []
        try:
            for inj in self.injectors:
                inj.apply()
                applied.append(inj)
        except BaseException:
            for inj in reversed(applied):
                try:
                    inj.revert()
                except Exception as e:  # noqa: BLE001 — best-effort unwind
                    logger.warning("chaos unwind failed: %s", e)
            raise
        return self.injectors

    def __exit__(self, *exc) -> None:
        for inj in reversed(self.injectors):
            try:
                inj.revert()
            except Exception as e:  # noqa: BLE001 — keep reverting the rest
                logger.warning("chaos revert failed: %s", e)


# ------------------------------------------------------------ pipeline chaos
class SlowPipelineStage(ChaosInjector):
    """Slow one stage of a running ``PipelinedTrainer``.

    ``revert()`` clears the injection on whatever actor currently holds
    the stage slot — after a remediation respawn that is a fresh actor
    which never saw the chaos, so revert degrades to a no-op."""

    def __init__(self, trainer, stage: int,
                 compute_delay_s: Optional[float] = None,
                 recv_delay_s: Optional[float] = None,
                 timeout: float = 30.0):
        self.trainer = trainer
        self.stage = stage
        self.spec: Dict[str, float] = {}
        if compute_delay_s:
            self.spec["compute_delay_s"] = compute_delay_s
        if recv_delay_s:
            self.spec["recv_delay_s"] = recv_delay_s
        self.timeout = timeout

    def _push(self, spec: Optional[Dict[str, float]]) -> None:
        import ray_tpu

        ray_tpu.get(
            self.trainer.stages[self.stage].inject_chaos.remote(spec),
            timeout=self.timeout,
        )

    def apply(self) -> "SlowPipelineStage":
        self._push(self.spec)
        return self

    def revert(self) -> None:
        try:
            self._push(None)
        except Exception as e:  # noqa: BLE001 — slot may hold a fresh (clean) actor
            logger.debug("SlowPipelineStage revert skipped: %s", e)


class KilledStageActor(ChaosInjector):
    """Kill a pipeline stage actor outright (one-shot; recovery is the
    system's job, so ``revert`` is a no-op).  Repeated kills inside one
    window are the restart-storm scenario."""

    def __init__(self, trainer, stage: int):
        self.trainer = trainer
        self.stage = stage

    def apply(self) -> "KilledStageActor":
        import ray_tpu

        ray_tpu.kill(self.trainer.stages[self.stage])
        return self

    def revert(self) -> None:
        return None


# --------------------------------------------------------------- serve chaos
class OverloadedServeReplica(ChaosInjector):
    """Closed-loop load: ``concurrency`` client threads each looping
    ``request_fn()`` until reverted.  Request failures are counted, not
    raised — overload chaos is allowed to shed."""

    def __init__(self, request_fn: Callable[[], Any], concurrency: int = 4,
                 name: str = "chaos-load"):
        self.request_fn = request_fn
        self.concurrency = concurrency
        self.name = name
        self.requests = 0
        self.errors = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._count_lock = threading.Lock()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.request_fn()
                with self._count_lock:
                    self.requests += 1
            except Exception:  # noqa: BLE001 — shed under overload is expected
                with self._count_lock:
                    self.errors += 1
                # Back off a beat so a hard-down target doesn't spin.
                self._stop.wait(0.1)

    def apply(self) -> "OverloadedServeReplica":
        self._stop.clear()
        self._threads = [
            threading.Thread(target=self._loop, name=f"{self.name}-{i}",
                             daemon=True)
            for i in range(self.concurrency)
        ]
        for t in self._threads:
            t.start()
        return self

    def revert(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        self._threads = []


# ---------------------------------------------------------- collective chaos
class CollectiveFabricMember:
    """One member of a simulated collective fabric, driven through the
    REAL tuner → flight-recorder → SLO pipeline.

    Each ``run_ops`` call asks the process-wide ``CollectiveTuner`` for
    an algorithm (real selection: heuristic seed → exploration → commit
    → decaying/forced re-probes), then models the transfer: a timed
    memcpy plus a duration computed from the fabric's per-algorithm
    bandwidth table, recorded via ``flight_recorder.record_collective``
    and fed back with ``tuner.observe`` — exactly the feedback loop the
    jax groups use.  A ``ThrottledCollectiveLink`` divides ONE
    algorithm's bandwidth on ONE member, which is what a degraded link
    looks like from that member's accounting.

    Deploy as an actor (``ray_tpu.remote(CollectiveFabricMember)``) so
    each member is its own process with its own tuner and metrics
    payload — the member-granular view the drift rule compares."""

    #: per-rank bandwidths (bytes/s) of the healthy fabric, per algorithm
    DEFAULT_BANDWIDTH = {"flat": 2e8, "ring": 8e8, "tree": 6e8,
                         "two_level": 5e8}

    def __init__(self, op: str = "allreduce", world_size: int = 4,
                 nbytes: int = 1 << 20,
                 algo_bandwidth: Optional[Dict[str, float]] = None):
        self.op = op
        self.world_size = world_size
        self.nbytes = nbytes
        self.algo_bandwidth = dict(
            self.DEFAULT_BANDWIDTH, **(algo_bandwidth or {})
        )
        self.throttle: Dict[str, float] = {}
        self._buf = bytearray(min(nbytes, 1 << 16))

    def set_throttle(self, algo: str, factor: Optional[float]) -> bool:
        if factor is None:
            self.throttle.pop(algo, None)
        else:
            self.throttle[algo] = float(factor)
        return True

    def run_ops(self, n: int = 1) -> str:
        from ray_tpu.collective import algorithms as alg
        from ray_tpu.collective.tuner import get_tuner
        from ray_tpu.util import flight_recorder

        tuner = get_tuner()
        candidates = alg.candidates_for(self.op, self.world_size, None)
        algo = ""
        for _ in range(n):
            decision = tuner.select(
                self.op, self.nbytes, self.world_size, None, candidates
            )
            algo = decision["algo"]
            bandwidth = self.algo_bandwidth.get(algo, 1e8)
            bandwidth /= self.throttle.get(algo, 1.0)
            # The modeled transfer: a real (small) memcpy so the op does
            # work, with the fabric model supplying the duration.
            bytes(self._buf)
            duration = self.nbytes / bandwidth
            flight_recorder.record_collective(
                self.op, "chaos", self.nbytes, self.world_size, duration,
                algo=algo, group="chaos_fabric",
            )
            tuner.observe(self.op, self.nbytes, self.world_size, None,
                          algo, bandwidth=self.nbytes / duration)
        return algo

    def committed(self) -> Optional[str]:
        """The tuner's committed algorithm for this member's bucket."""
        from ray_tpu.collective.tuner import get_tuner

        for row in get_tuner().stats().values():
            if row["op"] == self.op and row["world_size"] == self.world_size:
                return row["chosen"]
        return None

    def flush_metrics(self) -> bool:
        """Push this member's registry to the cluster KV now (tests can
        tighten the beat instead of waiting for the agent pull)."""
        from ray_tpu.util import metrics as _metrics

        _metrics.flush()
        return True


class ThrottledCollectiveLink(ChaosInjector):
    """Degrade one fabric member's bandwidth for one algorithm by
    ``factor`` (an actor handle to a ``CollectiveFabricMember``)."""

    def __init__(self, member, algo: str, factor: float = 50.0,
                 timeout: float = 30.0):
        self.member = member
        self.algo = algo
        self.factor = factor
        self.timeout = timeout

    def apply(self) -> "ThrottledCollectiveLink":
        import ray_tpu

        ray_tpu.get(
            self.member.set_throttle.remote(self.algo, self.factor),
            timeout=self.timeout,
        )
        return self

    def revert(self) -> None:
        import ray_tpu

        try:
            ray_tpu.get(
                self.member.set_throttle.remote(self.algo, None),
                timeout=self.timeout,
            )
        except Exception as e:  # noqa: BLE001 — member may already be gone
            logger.debug("ThrottledCollectiveLink revert skipped: %s", e)


# ----------------------------------------------------- control-plane chaos
class KilledLeader(ChaosInjector):
    """``kill -9`` the control-plane leader of an HA head node.

    The fault is the kill itself; recovery is the warm standby winning
    the lease, replaying the journal tail, and publishing the new
    endpoint — clients re-anchor through their resolver-backed retry
    clients without surfacing errors.  ``apply()`` records the epoch
    it deposed (``old_epoch``); tests assert the failover completed via
    ``node.wait_for_failover(old_epoch)``.  ``revert()`` respawns the
    dead candidate so the cluster leaves the scope with a warm standby
    again (repeated apply/revert cycles are the failover soak)."""

    def __init__(self, node):
        self.node = node
        self.old_epoch: int = 0

    def apply(self) -> "KilledLeader":
        self.old_epoch = self.node.kill_leader()
        return self

    def revert(self) -> None:
        try:
            self.node.ensure_standby()
        except Exception as e:  # noqa: BLE001 — node may be tearing down
            logger.debug("KilledLeader revert skipped: %s", e)


# ------------------------------------------------------- arbitration chaos
class PriorityBurst(ChaosInjector):
    """A high-priority placement-group burst landing on a busy cluster.

    ``apply()`` requests ``bundles`` at ``priority`` through the REAL
    create path — on a full cluster the control plane must
    checkpoint-then-evict lower-priority groups to place it (the
    latency-critical-serve-arrives scenario).  ``revert()`` removes the
    group, freeing the capacity so evicted victims auto-resume via the
    pending-PG drain.  ``placed`` records whether the burst actually
    landed within ``ready_timeout``."""

    def __init__(self, bundles: List[Dict[str, float]], priority: int = 1000,
                 strategy: str = "PACK", name: str = "chaos-burst",
                 ready_timeout: float = 30.0):
        self.bundles = [dict(b) for b in bundles]
        self.priority = priority
        self.strategy = strategy
        self.name = name
        self.ready_timeout = ready_timeout
        self.pg = None
        self.placed = False

    def apply(self) -> "PriorityBurst":
        from ray_tpu.core.placement import placement_group

        self.pg = placement_group(
            self.bundles, strategy=self.strategy, name=self.name,
            priority=self.priority,
        )
        self.placed = self.pg.ready(timeout=self.ready_timeout)
        return self

    def revert(self) -> None:
        if self.pg is None:
            return
        from ray_tpu.core.placement import remove_placement_group

        try:
            remove_placement_group(self.pg)
        except Exception as e:  # noqa: BLE001 — cluster may be tearing down
            logger.debug("PriorityBurst revert skipped: %s", e)
        self.pg = None


class QuotaHog(ChaosInjector):
    """A greedy tenant: floods the scheduler with ``count`` identical
    single-bundle placement groups from the calling job.

    With a job quota configured (``ray_tpu.init(job_quota=...)``) the
    over-quota tail queues at admission — never fails, never reserves —
    so the hog is contained to its cap while other tenants keep their
    capacity.  ``states()`` classifies the flood (CREATED vs PENDING);
    ``revert()`` removes every group, draining usage so any queued tail
    admits (and then gets removed too)."""

    def __init__(self, bundle: Dict[str, float], count: int,
                 strategy: str = "PACK", name: str = "chaos-hog",
                 settle_s: float = 1.0):
        self.bundle = dict(bundle)
        self.count = count
        self.strategy = strategy
        self.name = name
        self.settle_s = settle_s
        self.pgs: List[Any] = []

    def apply(self) -> "QuotaHog":
        from ray_tpu.core.placement import placement_group

        self.pgs = [
            placement_group(
                [dict(self.bundle)], strategy=self.strategy,
                name=f"{self.name}-{i}",
            )
            for i in range(self.count)
        ]
        # Let the group-commit sweep classify the flood before the test
        # reads states() — admission decisions are asynchronous.
        time.sleep(self.settle_s)
        return self

    def states(self) -> Dict[str, int]:
        """Current state histogram of the hog's groups."""
        from ray_tpu.core.core_worker import global_worker

        worker = global_worker()
        out: Dict[str, int] = {}
        for pg in self.pgs:
            info = worker._run_sync(
                worker.cp.call("get_placement_group", {"pg_id": pg.id})
            )
            state = info["state"] if info else "UNKNOWN"
            out[state] = out.get(state, 0) + 1
        return out

    def revert(self) -> None:
        from ray_tpu.core.placement import remove_placement_group

        for pg in self.pgs:
            try:
                remove_placement_group(pg)
            except Exception as e:  # noqa: BLE001 — keep removing the rest
                logger.debug("QuotaHog revert skipped: %s", e)
        self.pgs = []


# --------------------------------------------------------- provider chaos
class ProviderCreateErrors(ChaosInjector):
    """The next ``count`` ``create_node`` calls on a
    ``FakeMultiNodeProvider`` raise — the cloud API saying no (stockout,
    quota, rate limit).  Driven through the REAL reconcile loop, the
    autoscaler must converge to a backoff cadence per node type instead
    of a hot retry loop; recovery is the queued failures running out (or
    ``revert()`` clearing them early)."""

    def __init__(self, provider, count: int = 3):
        self.provider = provider
        self.count = count

    def apply(self) -> "ProviderCreateErrors":
        with self.provider._lock:
            self.provider.fault_create_errors += self.count
        return self

    def revert(self) -> None:
        with self.provider._lock:
            self.provider.fault_create_errors = 0


class SlowProvisioning(ChaosInjector):
    """Every ``create_node`` returns its provider id immediately but the
    node's processes start only after ``delay_s`` — a VM stuck in
    PROVISIONING.  The scaling decision must keep counting the pending
    node (it is in ``non_terminated_nodes``) and NOT double-launch while
    it boots."""

    def __init__(self, provider, delay_s: float = 3.0):
        self.provider = provider
        self.delay_s = delay_s

    def apply(self) -> "SlowProvisioning":
        with self.provider._lock:
            self.provider.fault_create_delay_s = self.delay_s
        return self

    def revert(self) -> None:
        with self.provider._lock:
            self.provider.fault_create_delay_s = 0.0


class NodeChurn(ChaosInjector):
    """Crash a launched node's processes while the provider record stays
    (the cloud still reports the VM running) — one-shot, like
    ``KilledStageActor``.  Recovery is two-sided: the control plane's
    health check declares the node dead (restarting its actors
    elsewhere), and the autoscaler's reclaim pass terminates the zombie
    provider record after ``reclaim_grace_s`` so a replacement can
    launch."""

    def __init__(self, provider, provider_id: str):
        self.provider = provider
        self.provider_id = provider_id

    def apply(self) -> "NodeChurn":
        self.provider.kill_node(self.provider_id)
        return self

    def revert(self) -> None:
        pass  # recovery is the system's job
