"""Search-space primitives and samplers (reference surface: ray
``python/ray/tune/search/`` — grid/random variant generation)."""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Sequence


class _Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class grid_search:  # noqa: N801 - matches the reference's API casing
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


class choice(_Domain):  # noqa: N801
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng):
        return rng.choice(self.values)


class uniform(_Domain):  # noqa: N801
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class loguniform(_Domain):  # noqa: N801
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class randint(_Domain):  # noqa: N801
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


def generate_variants(
    param_space: Dict[str, Any], num_samples: int, seed=None
) -> List[Dict[str, Any]]:
    """Cross-product over grid_search entries × num_samples draws of random
    domains (the reference's variant-generator semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, grid_search)]

    def expand_grids(base: Dict[str, Any], keys: List[str]):
        if not keys:
            yield dict(base)
            return
        k, rest = keys[0], keys[1:]
        for v in param_space[k].values:
            base[k] = v
            yield from expand_grids(base, rest)

    out = []
    for _ in range(max(1, num_samples)):
        for grid_combo in expand_grids({}, grid_keys):
            config = {}
            for k, v in param_space.items():
                if isinstance(v, grid_search):
                    config[k] = grid_combo[k]
                elif isinstance(v, _Domain):
                    config[k] = v.sample(rng)
                else:
                    config[k] = v
            out.append(config)
    return out
