"""Tree-structured Parzen Estimator search — the native model-based
searcher.

Reference surface: ray ``python/ray/tune/search/`` wraps external
model-based searchers (optuna/hyperopt — both TPE at their core); here the
algorithm is implemented natively so the framework has a self-contained
model-based option (round-1 gap: grid/random only).

Classic TPE (Bergstra et al., NeurIPS 2011): keep all observed
(config, score) pairs; split them into the best ``gamma`` fraction l(x)
and the rest g(x); model each hyperparameter dimension with a 1-D Parzen
(kernel density) estimator per split; sample candidates from l and pick
the one maximizing l(x)/g(x).  Continuous domains use gaussian kernels
(log-space for ``loguniform``), integers round, categoricals use smoothed
frequency weights.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from .search import _Domain, choice, loguniform, randint, uniform


class Searcher:
    """Sequential suggestion interface (reference: tune.search.Searcher)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, metrics: Dict[str, Any]):
        raise NotImplementedError


class TPESearcher(Searcher):
    def __init__(
        self,
        space: Dict[str, Any],
        metric: str = "loss",
        mode: str = "min",
        n_startup_trials: int = 8,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed: Optional[int] = None,
    ):
        for k, v in space.items():
            if isinstance(v, _Domain) and not isinstance(
                v, (uniform, loguniform, randint, choice)
            ):
                raise ValueError(f"unsupported domain for TPE: {k}={v!r}")
        self.space = space
        self.metric = metric
        self.mode = mode
        self.n_startup = n_startup_trials
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._live: Dict[str, Dict[str, Any]] = {}
        self._obs: List[Tuple[Dict[str, Any], float]] = []

    # ------------------------------------------------------------- protocol
    def suggest(self, trial_id: str) -> Dict[str, Any]:
        if len(self._obs) < self.n_startup:
            config = self._sample_random()
        else:
            config = self._sample_tpe()
        self._live[trial_id] = config
        return config

    def on_trial_complete(self, trial_id: str, metrics: Dict[str, Any]):
        config = self._live.pop(trial_id, None)
        if config is None or self.metric not in metrics:
            return
        score = float(metrics[self.metric])
        if self.mode == "max":
            score = -score
        self._obs.append((config, score))

    # ------------------------------------------------------------- sampling
    def _sample_random(self) -> Dict[str, Any]:
        out = {}
        for k, dom in self.space.items():
            out[k] = dom.sample(self.rng) if isinstance(dom, _Domain) else dom
        return out

    def _split(self):
        ranked = sorted(self._obs, key=lambda o: o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _sample_tpe(self) -> Dict[str, Any]:
        good, bad = self._split()
        best_cfg, best_ratio = None, -math.inf
        for _ in range(self.n_candidates):
            cfg, log_l, log_g = {}, 0.0, 0.0
            for key, dom in self.space.items():
                if not isinstance(dom, _Domain):
                    cfg[key] = dom
                    continue
                val, ll, lg = self._sample_dim(key, dom, good, bad)
                cfg[key] = val
                log_l += ll
                log_g += lg
            ratio = log_l - log_g
            if ratio > best_ratio:
                best_cfg, best_ratio = cfg, ratio
        return best_cfg or self._sample_random()

    # One dimension: draw from the good-split KDE, return the value and its
    # log-density under both splits.
    def _sample_dim(self, key, dom, good, bad):
        if isinstance(dom, choice):
            weights_g = self._cat_weights(key, dom, good)
            val = self.rng.choices(dom.values, weights=weights_g)[0]
            idx = dom.values.index(val)
            weights_b = self._cat_weights(key, dom, bad)
            return (
                val,
                math.log(weights_g[idx] / sum(weights_g)),
                math.log(weights_b[idx] / sum(weights_b)),
            )
        lo, hi, to_x, from_x = self._bounds(dom)
        xs_g = [to_x(c[key]) for c, _ in good]
        xs_b = [to_x(c[key]) for c, _ in bad]
        sigma = max((hi - lo) / max(2, len(xs_g)), 1e-12)
        center = self.rng.choice(xs_g) if xs_g else self.rng.uniform(lo, hi)
        x = min(max(self.rng.gauss(center, sigma), lo), hi)
        val = from_x(x)
        if isinstance(dom, randint):
            val = int(min(max(round(val), dom.low), dom.high - 1))
            x = float(val)
        return (
            val,
            self._kde_logpdf(x, xs_g, sigma, lo, hi),
            self._kde_logpdf(x, xs_b, sigma, lo, hi),
        )

    def _cat_weights(self, key, dom, split):
        counts = [1.0] * len(dom.values)  # +1 smoothing
        for cfg, _ in split:
            try:
                counts[dom.values.index(cfg[key])] += 1.0
            except (ValueError, KeyError):
                pass
        return counts

    @staticmethod
    def _bounds(dom):
        if isinstance(dom, loguniform):
            return (
                math.log(dom.low), math.log(dom.high), math.log, math.exp,
            )
        if isinstance(dom, randint):
            return float(dom.low), float(dom.high - 1), float, float
        return dom.low, dom.high, float, float

    @staticmethod
    def _kde_logpdf(x, xs, sigma, lo, hi):
        # Mixture of gaussians around observations + one uniform component
        # (keeps densities positive everywhere, the TPE prior smoothing).
        span = max(hi - lo, 1e-12)
        parts = [1.0 / span]
        for c in xs:
            z = (x - c) / sigma
            parts.append(
                math.exp(-0.5 * z * z) / (sigma * math.sqrt(2 * math.pi))
            )
        return math.log(sum(parts) / len(parts))
