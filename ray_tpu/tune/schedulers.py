"""Trial schedulers (reference: ray ``python/ray/tune/schedulers/`` —
FIFO, ASHA/async-hyperband, HyperBand brackets, median stopping, and
population-based training).

Protocol: ``on_result(trial_id, metrics, **info) -> "CONTINUE" | "STOP"``;
``info`` may carry ``config`` and ``checkpoint``.  A scheduler that clones
trials (PBT) also implements ``pop_clones() -> [(config, checkpoint)]``,
which the Tuner drains into new trials.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Tuple


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: Dict, **info) -> str:
        return "CONTINUE"


class ASHAScheduler:
    """Asynchronous Successive Halving: at each rung (grace_period ×
    reduction_factor^k iterations), stop trials not in the top 1/rf of
    completed rung results (ray ``tune/schedulers/async_hyperband.py``)."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
        time_attr: str = "training_iteration",
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung level -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = {}
        r = grace_period
        self._rung_levels = []
        while r < max_t:
            self._rung_levels.append(r)
            r *= reduction_factor

    def on_result(self, trial_id: str, metrics: Dict, **info) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return "CONTINUE"
        if t >= self.max_t:
            return "STOP"
        for rung in reversed(self._rung_levels):
            if t == rung:
                recorded = self._rungs.setdefault(rung, [])
                recorded.append(float(value))
                if len(recorded) < self.rf:
                    return "CONTINUE"  # not enough peers to judge
                ordered = sorted(
                    recorded, reverse=(self.mode == "max")
                )
                cutoff_idx = max(0, len(ordered) // self.rf - 1)
                cutoff = ordered[cutoff_idx]
                good = (
                    value >= cutoff if self.mode == "max" else value <= cutoff
                )
                return "CONTINUE" if good else "STOP"
        return "CONTINUE"


class MedianStoppingRule:
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference:
    ``tune/schedulers/median_stopping_rule.py``)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1,
                 time_attr: str = "training_iteration",
                 min_samples_required: int = 3):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.time_attr = time_attr
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = {}

    def on_result(self, trial_id: str, metrics: Dict, **info) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return "CONTINUE"
        self._history.setdefault(trial_id, []).append(float(value))
        if t < self.grace_period:
            return "CONTINUE"
        other_avgs = [
            sum(v) / len(v)
            for tid, v in self._history.items()
            if tid != trial_id and v
        ]
        if len(other_avgs) < self.min_samples:
            return "CONTINUE"
        other_avgs.sort()
        median = other_avgs[len(other_avgs) // 2]
        mine = self._history[trial_id]
        best = max(mine) if self.mode == "max" else min(mine)
        bad = best < median if self.mode == "max" else best > median
        return "STOP" if bad else "CONTINUE"


class HyperBandScheduler:
    """HyperBand as a set of ASHA brackets with staggered grace periods
    (reference: ``tune/schedulers/hyperband.py``; the async-bracket framing
    follows the ASHA paper's recommendation)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 81, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        self.brackets: List[ASHAScheduler] = []
        grace = 1
        while grace < max_t:
            self.brackets.append(
                ASHAScheduler(
                    metric=metric, mode=mode, max_t=max_t,
                    grace_period=grace, reduction_factor=reduction_factor,
                    time_attr=time_attr,
                )
            )
            grace *= reduction_factor
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def on_result(self, trial_id: str, metrics: Dict, **info) -> str:
        idx = self._assignment.get(trial_id)
        if idx is None:
            idx = self._next % len(self.brackets)
            self._assignment[trial_id] = idx
            self._next += 1
        return self.brackets[idx].on_result(trial_id, metrics, **info)


class PopulationBasedTraining:
    """PBT (reference: ``tune/schedulers/pbt.py``): at each perturbation
    interval, trials in the bottom quantile stop and are replaced by clones
    of a top-quantile trial — config mutated, training state restored from
    the donor's last reported checkpoint."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 quantile_fraction: float = 0.25,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 time_attr: str = "training_iteration",
                 seed: int = 0):
        assert mode in ("min", "max")
        assert 0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.quantile = quantile_fraction
        self.mutations = hyperparam_mutations or {}
        self.time_attr = time_attr
        self._rng = random.Random(seed)
        # trial_id -> {"score", "config", "checkpoint"}
        self._state: Dict[str, dict] = {}
        self._clones: List[Tuple[dict, Any]] = []
        self.num_perturbations = 0

    def _mutate(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if callable(getattr(spec, "sample", None)):
                out[key] = spec.sample(self._rng)
            elif isinstance(spec, (list, tuple)):
                out[key] = self._rng.choice(list(spec))
            elif callable(spec):
                out[key] = spec()
            elif isinstance(out.get(key), (int, float)):
                # resample-by-perturbation: ×0.8 or ×1.2 (reference default)
                factor = self._rng.choice([0.8, 1.2])
                val = out[key] * factor
                if isinstance(out[key], int):
                    # round, and force at least ±1 so small ints (1, 2)
                    # don't truncate to 0 or get stuck forever
                    val = round(val)
                    if val == out[key]:
                        val = out[key] + (1 if factor > 1 else -1)
                    out[key] = max(1, int(val))
                else:
                    out[key] = float(val)
        return out

    def on_result(self, trial_id: str, metrics: Dict, **info) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if value is None:
            return "CONTINUE"
        self._state[trial_id] = {
            "score": float(value),
            "config": info.get("config", {}),
            "checkpoint": info.get("checkpoint"),
        }
        if info.get("terminal"):
            # Trial is ending via stop criteria: its score stays as a donor
            # comparator, but it must never be exploited (a clone per
            # finished trial would keep the experiment alive forever).
            return "CONTINUE"
        if t is None or t % self.interval != 0:
            return "CONTINUE"
        scores = sorted(
            (s["score"] for s in self._state.values()),
            reverse=(self.mode == "max"),
        )
        if len(scores) < 3:
            return "CONTINUE"
        k = max(1, int(len(scores) * self.quantile))
        top_cut, bottom_cut = scores[k - 1], scores[-k]
        mine = self._state[trial_id]["score"]
        in_bottom = (
            mine <= bottom_cut if self.mode == "max" else mine >= bottom_cut
        )
        if not in_bottom:
            return "CONTINUE"
        donors = [
            s for s in self._state.values()
            if (s["score"] >= top_cut if self.mode == "max"
                else s["score"] <= top_cut)
        ]
        if not donors:
            return "CONTINUE"
        donor = self._rng.choice(donors)
        self._clones.append(
            (self._mutate(donor["config"]), donor["checkpoint"])
        )
        self.num_perturbations += 1
        self._state.pop(trial_id, None)
        return "STOP"

    def pop_clones(self) -> List[Tuple[dict, Any]]:
        clones, self._clones = self._clones, []
        return clones


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: ``tune/schedulers/pb2.py``,
    Parker-Holder et al. 2020): PBT where the explore step picks new
    hyperparameters by maximizing a GP-UCB acquisition fit on observed
    (config, score-improvement) data, instead of random perturbation.
    The GP is a small native numpy RBF-kernel regressor over configs
    normalized into [0,1]^d by ``hyperparam_bounds``.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 quantile_fraction: float = 0.25,
                 hyperparam_bounds: Optional[Dict[str, Tuple[float, float]]] = None,
                 ucb_kappa: float = 1.5,
                 n_candidates: int = 64,
                 seed: int = 0):
        super().__init__(
            metric=metric, mode=mode,
            perturbation_interval=perturbation_interval,
            quantile_fraction=quantile_fraction,
            hyperparam_mutations=None, seed=seed,
        )
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds={key: (lo, hi)}")
        self.bounds = dict(hyperparam_bounds)
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        # Observations: (normalized config vector, score improvement).
        self._gp_x: List[List[float]] = []
        self._gp_y: List[float] = []
        self._last_score: Dict[str, float] = {}

    # ----------------------------------------------------------------- data
    def _norm(self, config: dict) -> List[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return out

    def on_result(self, trial_id: str, metrics: Dict, **info) -> str:
        value = metrics.get(self.metric)
        if value is not None:
            prev = self._last_score.get(trial_id)
            if prev is not None:
                delta = float(value) - prev
                if self.mode == "min":
                    delta = -delta  # improvement = decrease
                self._gp_x.append(self._norm(info.get("config", {})))
                self._gp_y.append(delta)
            self._last_score[trial_id] = float(value)
        return super().on_result(trial_id, metrics, **info)

    # -------------------------------------------------------------- explore
    def _mutate(self, config: dict) -> dict:
        """GP-UCB over the bounded keys (the PB2 explore step)."""
        import numpy as np

        out = dict(config)
        if len(self._gp_y) < 3:
            for k, (lo, hi) in self.bounds.items():
                out[k] = lo + self._rng.random() * (hi - lo)
            return out
        X = np.asarray(self._gp_x[-64:], dtype=float)
        y = np.asarray(self._gp_y[-64:], dtype=float)
        y_std = y.std() or 1.0
        yn = (y - y.mean()) / y_std
        ell, noise = 0.2, 1e-3
        def rbf(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (ell * ell))
        K = rbf(X, X) + noise * np.eye(len(X))
        Kinv_y = np.linalg.solve(K, yn)
        cands = np.asarray(
            [
                [self._rng.random() for _ in self.bounds]
                for _ in range(self.n_candidates)
            ]
        )
        Ks = rbf(cands, X)
        mu = Ks @ Kinv_y
        var = np.maximum(
            1.0 - np.einsum("ij,jk,ik->i", Ks, np.linalg.inv(K), Ks), 1e-9
        )
        ucb = mu + self.kappa * np.sqrt(var)
        best = cands[int(np.argmax(ucb))]
        for i, (k, (lo, hi)) in enumerate(self.bounds.items()):
            val = lo + float(best[i]) * (hi - lo)
            if isinstance(config.get(k), int):
                val = int(round(val))
            out[k] = val
        return out
