"""Trial schedulers (reference: ray ``python/ray/tune/schedulers/`` —
FIFO and ASHA/async-hyperband early stopping)."""

from __future__ import annotations

from typing import Dict, List


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: Dict) -> str:
        return "CONTINUE"


class ASHAScheduler:
    """Asynchronous Successive Halving: at each rung (grace_period ×
    reduction_factor^k iterations), stop trials not in the top 1/rf of
    completed rung results (ray ``tune/schedulers/async_hyperband.py``)."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
        time_attr: str = "training_iteration",
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung level -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = {}
        r = grace_period
        self._rung_levels = []
        while r < max_t:
            self._rung_levels.append(r)
            r *= reduction_factor

    def on_result(self, trial_id: str, metrics: Dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return "CONTINUE"
        if t >= self.max_t:
            return "STOP"
        for rung in reversed(self._rung_levels):
            if t == rung:
                recorded = self._rungs.setdefault(rung, [])
                recorded.append(float(value))
                if len(recorded) < self.rf:
                    return "CONTINUE"  # not enough peers to judge
                ordered = sorted(
                    recorded, reverse=(self.mode == "max")
                )
                cutoff_idx = max(0, len(ordered) // self.rf - 1)
                cutoff = ordered[cutoff_idx]
                good = (
                    value >= cutoff if self.mode == "max" else value <= cutoff
                )
                return "CONTINUE" if good else "STOP"
        return "CONTINUE"
