from .search import choice, grid_search, loguniform, randint, uniform  # noqa: F401
from .schedulers import ASHAScheduler, FIFOScheduler  # noqa: F401
from .tuner import ResultGrid, TuneConfig, Tuner, TrialResult  # noqa: F401
