from .search import choice, grid_search, loguniform, randint, uniform  # noqa: F401
from .schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
)
from .tpe import Searcher, TPESearcher  # noqa: F401
from .tuner import ResultGrid, TuneConfig, Tuner, TrialResult  # noqa: F401
