from .search import choice, grid_search, loguniform, randint, uniform  # noqa: F401
from .schedulers import (  # noqa: F401
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from .tuner import ResultGrid, TuneConfig, Tuner, TrialResult  # noqa: F401
