"""Tuner — trial orchestration loop.

Reference architecture (ray ``python/ray/tune/tuner.py:43`` +
``tune/execution/tune_controller.py:68``): an event loop manages trials as
remote actors, consuming search-algorithm variants, feeding results to the
scheduler (ASHA early stopping), bounded by max_concurrent_trials.  Trials
here are actors running the trainable function with a session that queues
``report`` results (same session machinery as Train, which is how the
reference layers Train-on-Tune).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.serialization import dumps_function, loads_function

from .schedulers import FIFOScheduler
from .search import generate_variants


@dataclasses.dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent_trials: int = 2
    metric: str = "loss"
    mode: str = "min"
    scheduler: Any = None
    # Sequential model-based searcher (e.g. TPESearcher); when set,
    # configs are suggested lazily as capacity frees and completed results
    # feed the model (reference: tune.search.Searcher protocol).
    search_alg: Any = None
    seed: Optional[int] = None
    # Stop criteria applied to every trial's metrics, e.g.
    # {"training_iteration": 20} (reference: RunConfig(stop=...)).
    stop: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    error: Optional[str] = None
    stopped_early: bool = False


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self.results = results
        self._metric = metric
        self._mode = mode

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [
            r for r in self.results
            if r.error is None and metric in (r.metrics or {})
        ]
        if not scored:
            raise ValueError("no successful trials with the target metric")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric]
        )

    def __len__(self):
        return len(self.results)


@ray_tpu.remote
class _TrialActor:
    """Runs one trial; queues reported metrics for the controller."""

    def __init__(self, trial_id: str):
        self.trial_id = trial_id
        self._lock = threading.Lock()
        self._queue: List[Dict[str, Any]] = []
        self._stop = False

    def run(self, fn_payload: bytes, config: Dict[str, Any],
            start_checkpoint=None, stop_criteria=None):
        from ray_tpu.train.session import TrainContext, _clear_session, _set_session

        fn = loads_function(fn_payload)
        iteration = [0]

        ctx = TrainContext(
            world_rank=0, world_size=1, local_rank=0, node_rank=0,
            trial_name=self.trial_id, _report_fn=None,
            latest_checkpoint=start_checkpoint,
        )

        def report_fn(metrics, checkpoint):
            iteration[0] += 1
            metrics = dict(metrics)
            metrics.setdefault("training_iteration", iteration[0])
            if checkpoint is not None:
                ctx.latest_checkpoint = checkpoint
            with self._lock:
                self._queue.append((metrics, checkpoint))
            # Stop criteria apply synchronously (a fast trainable would
            # otherwise race past the controller's asynchronous poll).
            if _met_stop_criteria(metrics, stop_criteria):
                raise _EarlyStop()
            if self._stop:
                raise _EarlyStop()

        ctx._report_fn = report_fn
        _set_session(ctx)
        try:
            fn(config)
            return {"ok": True, "stopped": False}
        except _EarlyStop:
            return {"ok": True, "stopped": True}
        finally:
            _clear_session()

    def poll(self):
        with self._lock:
            q, self._queue = self._queue, []
            return q

    def request_stop(self):
        self._stop = True
        return True


class _EarlyStop(BaseException):
    pass


def _met_stop_criteria(metrics: Dict[str, Any],
                       stop: Optional[Dict[str, float]]) -> bool:
    return bool(stop) and any(
        metrics.get(k) is not None and metrics[k] >= v
        for k, v in stop.items()
    )


def _all_subclasses(cls):
    for sub in cls.__subclasses__():
        yield sub
        yield from _all_subclasses(sub)


def _as_function_trainable(trainable):
    """Accept both function trainables (``fn(config)``) and class
    trainables exposing the Algorithm lifecycle (``setup/train/stop`` —
    e.g. an rllib Algorithm class or AlgorithmConfig).  Class trainables
    wrap into a report loop (reference: class Trainable adaptation)."""
    from ..rllib.algorithm import Algorithm, AlgorithmConfig

    if isinstance(trainable, type) and issubclass(trainable, Algorithm):
        algo_cls = trainable

        def run_algo(config):
            from ray_tpu.train import session as train_session

            cfg_cls = None
            for sub in _all_subclasses(AlgorithmConfig):
                if sub.ALGO_CLS is algo_cls:
                    cfg_cls = sub
                    break
            algo_cfg = (cfg_cls or AlgorithmConfig)()
            if config:
                algo_cfg.training(**config)
            algo = algo_cls(algo_cfg)
            try:
                while True:
                    result = algo.train()
                    train_session.report(result)
            finally:
                algo.stop()

        return run_algo
    if not callable(trainable):
        raise TypeError(f"trainable must be callable, got {trainable!r}")
    return trainable


class Tuner:
    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], None],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
    ):
        self.trainable = _as_function_trainable(trainable)
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()

    def fit(self) -> ResultGrid:
        from ray_tpu.core.usage import record_library_usage

        record_library_usage("tune")
        cfg = self.tune_config
        scheduler = cfg.scheduler or FIFOScheduler()
        searcher = cfg.search_alg
        payload = dumps_function(self.trainable)
        if searcher is None:
            variants = generate_variants(
                self.param_space, cfg.num_samples, cfg.seed
            )
            pending = [
                (f"trial_{i:04d}", variant, None)
                for i, variant in enumerate(variants)
            ]
            to_suggest = 0
            next_trial = len(pending)
        else:
            pending = []
            to_suggest = cfg.num_samples
            next_trial = cfg.num_samples
        running: Dict[str, dict] = {}
        results: List[TrialResult] = []

        while pending or running or to_suggest > 0:
            while (
                searcher is not None
                and to_suggest > 0
                and len(pending) + len(running) < cfg.max_concurrent_trials
            ):
                tid = f"trial_{cfg.num_samples - to_suggest:04d}"
                to_suggest -= 1
                pending.append((tid, searcher.suggest(tid), None))
            while pending and len(running) < cfg.max_concurrent_trials:
                trial_id, variant, start_ckpt = pending.pop(0)
                # max_concurrency: poll()/request_stop() must stay responsive
                # while run() executes the trainable.
                actor = _TrialActor.options(max_concurrency=4).remote(trial_id)
                running[trial_id] = {
                    "actor": actor,
                    "config": variant,
                    "ref": actor.run.remote(
                        payload, variant, start_ckpt, cfg.stop
                    ),
                    "history": [],
                    "stopped": False,
                }
            time.sleep(0.05)
            for trial_id, st in list(running.items()):
                for metrics, checkpoint in ray_tpu.get(
                    st["actor"].poll.remote(), timeout=60
                ):
                    st["history"].append(metrics)
                    terminal = _met_stop_criteria(metrics, cfg.stop)
                    decision = scheduler.on_result(
                        trial_id, metrics,
                        config=st["config"], checkpoint=checkpoint,
                        terminal=terminal,
                    )
                    if decision != "STOP" and terminal:
                        decision = "STOP"
                    if decision == "STOP" and not st["stopped"]:
                        st["stopped"] = True
                        st["actor"].request_stop.remote()
                ready, _ = ray_tpu.wait([st["ref"]], timeout=0)
                if ready:
                    error = None
                    stopped = st["stopped"]
                    try:
                        out = ray_tpu.get(st["ref"], timeout=10)
                        stopped = stopped or out.get("stopped", False)
                    except Exception as e:  # noqa: BLE001
                        error = str(e)
                    # Final drain after completion — a fast trial may have
                    # reported everything before the first poll, so these
                    # results must still reach the scheduler (PBT clone
                    # decisions depend on them).
                    try:
                        for metrics, ckpt in ray_tpu.get(
                            st["actor"].poll.remote(), timeout=30
                        ):
                            st["history"].append(metrics)
                            scheduler.on_result(
                                trial_id, metrics,
                                config=st["config"], checkpoint=ckpt,
                                terminal=_met_stop_criteria(
                                    metrics, cfg.stop
                                ),
                            )
                    except Exception:
                        pass
                    if searcher is not None:
                        final = dict(st["history"][-1]) if st["history"] else {}
                        if error is not None:
                            final["error"] = True
                        # Always fires (even for crashed/report-less trials)
                        # so the searcher's live-trial table cannot leak.
                        searcher.on_trial_complete(trial_id, final)
                    results.append(
                        TrialResult(
                            trial_id=trial_id,
                            config=st["config"],
                            metrics=st["history"][-1] if st["history"] else {},
                            metrics_history=st["history"],
                            error=error,
                            stopped_early=stopped,
                        )
                    )
                    try:
                        ray_tpu.kill(st["actor"])
                    except Exception:
                        pass
                    del running[trial_id]
            # PBT-style clones: enqueue replacements for exploited trials
            # (checked after draining so end-of-trial decisions count).
            if hasattr(scheduler, "pop_clones"):
                for clone_cfg, clone_ckpt in scheduler.pop_clones():
                    pending.append(
                        (f"trial_{next_trial:04d}", clone_cfg, clone_ckpt)
                    )
                    next_trial += 1
        return ResultGrid(results, cfg.metric, cfg.mode)
