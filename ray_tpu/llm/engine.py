"""JAX LLM engine: slot-based continuous batching over a KV cache.

Role-equivalent of the reference's vLLM engine wrapper (ray
``python/ray/llm/_internal/serve/engines/vllm/``) — but the engine IS the
TPU program: a fixed pool of batch slots shares one jitted decode step, so
requests join and leave the batch at token granularity (continuous
batching) and the chip never waits for the longest request in a batch.

Model-agnostic: any config type with a registered ``ModelFamily``
(``ray_tpu.models.model_family`` — GPT-2 and Llama ship in-tree, mirroring
the reference's vLLM model registry) plugs in; the engine only speaks
init/init_cache/prefill/decode_step.

Shapes are static (max_batch_size × max_seq_len) so XLA compiles exactly
two programs: prefill and decode.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..models import GPT2Config, model_family
from ..models.gpt2_decode import sample_logits
from .tokenizer import ByteTokenizer


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0
    top_p: float = 1.0
    stop_token: Optional[int] = None  # default: tokenizer EOS


@dataclasses.dataclass
class EngineConfig:
    # Any config with a registered ModelFamily (GPT2Config, LlamaConfig, …).
    model: Any = dataclasses.field(
        default_factory=lambda: GPT2Config.tiny(vocab_size=384)
    )
    max_batch_size: int = 8
    max_seq_len: int = 128
    seed: int = 0
    # Optional: callable returning trained params (checkpoint load); default
    # random init (tests / smoke).
    param_loader: Optional[Callable[[], Any]] = None


def encode_prompt(tokenizer, prompt: str, max_seq_len: int) -> List[int]:
    """Tokenize + left-truncate to the cache budget — the ONE place prompt
    shaping happens (the disagg prefill role must match the monolithic
    engine byte-for-byte or outputs diverge)."""
    token_ids = tokenizer.encode(prompt)
    return token_ids[-(max_seq_len - 1):]


@dataclasses.dataclass
class _Slot:
    request_id: int
    prompt_len: int
    generated: List[int]
    params: SamplingParams
    done: bool = False

    @property
    def last_pos(self) -> int:
        """Cache position of the most recent token."""
        return self.prompt_len + len(self.generated) - 1


class JaxLLMEngine:
    def __init__(self, cfg: EngineConfig, tokenizer=None):
        import jax

        self.cfg = cfg
        self.tokenizer = tokenizer or ByteTokenizer()
        mcfg = cfg.model
        fam = model_family(mcfg)
        self.family = fam
        if cfg.param_loader is not None:
            self.params = cfg.param_loader()
        else:
            self.params = fam.init(jax.random.PRNGKey(cfg.seed), mcfg)
        self._key = jax.random.PRNGKey(cfg.seed + 1)
        self.cache = fam.init_cache(mcfg, cfg.max_batch_size, cfg.max_seq_len)
        # Per-slot state; None = free.
        self.slots: List[Optional[_Slot]] = [None] * cfg.max_batch_size
        self._next_id = itertools.count()
        self._waiting: List[tuple] = []  # (request_id, token_ids, params)
        self._finished: Dict[int, dict] = {}
        # ALL engine-state mutation serializes on this lock: step() may be
        # driven concurrently by batched calls (replica event loop) and by
        # generate_stream callers (replica executor threads).  Reentrant:
        # generate/generate_stream hold it across pop+step.
        import threading

        self._step_lock = threading.RLock()

        def prefill_one(params, cache, tokens, length, slot_idx):
            """Prefill a single request into batch row ``slot_idx``."""
            import jax.numpy as jnp

            one_cache = fam.init_cache(mcfg, 1, cfg.max_seq_len)
            logits, one_cache = fam.prefill(
                params, tokens[None], jnp.asarray([length]), one_cache, mcfg
            )
            cache = {
                "k": jax.lax.dynamic_update_slice(
                    cache["k"], one_cache["k"], (0, slot_idx, 0, 0, 0)
                ),
                "v": jax.lax.dynamic_update_slice(
                    cache["v"], one_cache["v"], (0, slot_idx, 0, 0, 0)
                ),
            }
            return logits[0], cache

        self._prefill_one = jax.jit(prefill_one, donate_argnums=(1,))

        def insert_kv(cache, k1, v1, idx):
            """Splice a prefilled single-row KV block into batch row idx
            (disaggregated admission — the row arrives from a prefill
            replica instead of the local prefill program)."""
            return {
                "k": jax.lax.dynamic_update_slice(
                    cache["k"], k1, (0, idx, 0, 0, 0)
                ),
                "v": jax.lax.dynamic_update_slice(
                    cache["v"], v1, (0, idx, 0, 0, 0)
                ),
            }

        self._insert_kv = jax.jit(insert_kv, donate_argnums=(0,))
        self._waiting_kv: List[tuple] = []  # (rid, meta, k, v)
        self._decode = jax.jit(
            lambda params, cache, tokens, pos: fam.decode_step(
                params, tokens, pos, cache, mcfg
            ),
            donate_argnums=(1,),
        )
        # Sampling params are static: Python branches inside sample_logits;
        # one small compile per distinct SamplingParams config.
        self._sample = jax.jit(
            sample_logits,
            static_argnames=("temperature", "top_k", "top_p"),
        )

    # ----------------------------------------------------------------- queue
    def add_request(
        self, prompt: str, params: Optional[SamplingParams] = None
    ) -> int:
        params = params or SamplingParams()
        token_ids = encode_prompt(self.tokenizer, prompt, self.cfg.max_seq_len)
        request_id = next(self._next_id)
        self._waiting.append((request_id, token_ids, params))
        return request_id

    def add_request_from_kv(self, meta: dict, k, v) -> int:
        """Disaggregated admission: enqueue a request whose prompt was
        prefilled elsewhere.  ``meta`` carries prompt_len / first_token /
        sampling (see llm.disagg.PrefillEngine.prefill); ``k``/``v`` are
        the [L, 1, H, S, D] KV pages for the prompt."""
        import jax.numpy as jnp

        with self._step_lock:
            request_id = next(self._next_id)
            self._waiting_kv.append(
                (request_id, meta, jnp.asarray(k), jnp.asarray(v))
            )
            return request_id

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _admit_kv(self):
        """Drain adopted-KV requests into free slots (no local prefill)."""
        while self._waiting_kv:
            idx = self._free_slot()
            if idx is None:
                return
            request_id, meta, k, v = self._waiting_kv.pop(0)
            self.cache = self._insert_kv(self.cache, k, v, idx)
            slot = _Slot(
                request_id=request_id,
                prompt_len=meta["prompt_len"],
                generated=[meta["first_token"]],
                params=meta["sampling"],
            )
            self.slots[idx] = slot
            self._check_done(slot, meta["first_token"])

    def _admit(self):
        import jax.numpy as jnp

        self._admit_kv()
        while self._waiting:
            idx = self._free_slot()
            if idx is None:
                return
            request_id, token_ids, params = self._waiting.pop(0)
            tokens = np.zeros(self.cfg.max_seq_len, np.int32)
            tokens[: len(token_ids)] = token_ids
            logits, self.cache = self._prefill_one(
                self.params,
                self.cache,
                jnp.asarray(tokens),
                len(token_ids),
                idx,
            )
            first = self._sample_one(logits[None], params)[0]
            slot = _Slot(
                request_id=request_id,
                prompt_len=len(token_ids),
                generated=[int(first)],
                params=params,
            )
            self.slots[idx] = slot
            self._check_done(slot, int(first))

    def _sample_one(self, logits, params: SamplingParams):
        import jax

        self._key, sub = jax.random.split(self._key)
        return np.asarray(
            self._sample(
                logits,
                sub,
                temperature=params.temperature,
                top_k=params.top_k,
                top_p=params.top_p,
            )
        )

    def _check_done(self, slot: _Slot, token: int):
        stop = (
            slot.params.stop_token
            if slot.params.stop_token is not None
            else getattr(self.tokenizer, "EOS", None)
        )
        total_len = slot.prompt_len + len(slot.generated)
        if (
            (stop is not None and token == stop)
            or len(slot.generated) >= slot.params.max_tokens
            or total_len >= self.cfg.max_seq_len - 1
        ):
            slot.done = True

    # ------------------------------------------------------------------ step
    def step(self) -> List[dict]:
        """Admit waiting requests, run ONE decode step for all active slots,
        retire finished requests.  Returns newly finished outputs.
        Thread-safe (serialized on the engine lock)."""
        import jax.numpy as jnp

        with self._step_lock:
            return self._step_locked(jnp)

    def _step_locked(self, jnp) -> List[dict]:
        self._admit()
        finished = self._retire()  # requests that finished at admission
        active = [
            (i, s) for i, s in enumerate(self.slots)
            if s is not None and not s.done
        ]
        if active:
            tokens = np.zeros(self.cfg.max_batch_size, np.int32)
            pos = np.zeros(self.cfg.max_batch_size, np.int32)
            for i, s in active:
                tokens[i] = s.generated[-1]
                pos[i] = s.last_pos
            logits, self.cache = self._decode(
                self.params, self.cache, jnp.asarray(tokens), jnp.asarray(pos)
            )
            logits_np = logits  # stays on device for sampling
            for i, s in active:
                token = int(
                    self._sample_one(logits_np[i : i + 1], s.params)[0]
                )
                s.generated.append(token)
                self._check_done(s, token)
        finished.extend(self._retire())
        return finished

    def _retire(self) -> List[dict]:
        out = []
        for i, s in enumerate(self.slots):
            if s is not None and s.done:
                gen = s.generated
                stop = (
                    s.params.stop_token
                    if s.params.stop_token is not None
                    else getattr(self.tokenizer, "EOS", None)
                )
                if stop is not None and gen and gen[-1] == stop:
                    gen = gen[:-1]
                result = {
                    "request_id": s.request_id,
                    "token_ids": gen,
                    "text": self.tokenizer.decode(gen),
                    "num_generated": len(s.generated),
                }
                self._finished[s.request_id] = result
                out.append(result)
                self.slots[i] = None
        return out

    def has_unfinished(self) -> bool:
        return bool(self._waiting) or bool(self._waiting_kv) or any(
            s is not None for s in self.slots
        )

    # ------------------------------------------------------------- generate
    def cancel_request(self, request_id: int) -> None:
        """Drop a request wherever it is (queue, slot, finished results) —
        abandoned streams must not keep decoding or park results forever."""
        with self._step_lock:
            self._waiting = [
                w for w in self._waiting if w[0] != request_id
            ]
            self._waiting_kv = [
                w for w in self._waiting_kv if w[0] != request_id
            ]
            for i, slot in enumerate(self.slots):
                if slot is not None and slot.request_id == request_id:
                    self.slots[i] = None
            self._finished.pop(request_id, None)

    def generate_stream(self, prompt: str,
                        params: Optional[SamplingParams] = None,
                        timeout_s: float = 300.0):
        """Incremental generation: yields the text delta after every decode
        step for this request.  Concurrent streams (and batched generate
        calls) share the slot pool — every state access holds the engine
        lock; only the yields happen outside it."""
        yield from self.stream_request(
            self.add_request(prompt, params), timeout_s
        )

    def stream_request(self, request_id: int, timeout_s: float = 300.0):
        """Stream an ALREADY-QUEUED request's deltas (the disaggregated
        streaming path: the id came from add_request_from_kv, whose prompt
        was prefilled on another replica)."""
        emitted = 0
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError("generation exceeded timeout")
                done = None
                delta_tokens: list = []
                with self._step_lock:
                    done = self._finished.pop(request_id, None)
                    if done is None:
                        self.step()
                        done = self._finished.pop(request_id, None)
                    if done is None:
                        slot = next(
                            (s for s in self.slots
                             if s is not None
                             and s.request_id == request_id),
                            None,
                        )
                        if slot is not None and len(slot.generated) > emitted:
                            delta_tokens = list(slot.generated[emitted:])
                            emitted += len(delta_tokens)
                if done is not None:
                    tail = self.tokenizer.decode(done["token_ids"][emitted:])
                    if tail:
                        yield tail
                    return
                if delta_tokens:
                    text = self.tokenizer.decode(delta_tokens)
                    if text:
                        yield text
        finally:
            # Timeout or abandoned consumer: release the slot/queue entry.
            self.cancel_request(request_id)

    def generate(
        self,
        prompts: List[str],
        params: Optional[SamplingParams] = None,
        timeout_s: float = 300.0,
    ) -> List[dict]:
        """Blocking batch generation (requests stream through the slot pool
        regardless of len(prompts) vs max_batch_size).  Returns as soon as
        THIS call's requests are done — a concurrent caller's in-flight
        work must not delay this caller's results (every caller used to
        spin until the whole engine drained)."""
        ids = [self.add_request(p, params) for p in prompts]
        deadline = time.monotonic() + timeout_s
        while True:
            with self._step_lock:
                if all(i in self._finished for i in ids):
                    return [self._finished.pop(i) for i in ids]
                self.step()
            if time.monotonic() > deadline:
                raise TimeoutError("generation exceeded timeout")
