"""Batch LLM inference over the Data layer.

Reference: ray ``python/ray/llm/_internal/batch/`` (the ``Processor``
pipeline applying a vLLM stage to a Dataset via actor pools).  Here the
stage is a stateful UDF holding a ``JaxLLMEngine``, executed by
``map_batches(compute=ActorPoolStrategy(...))`` so the engine loads once
per actor and blocks stream through.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .engine import EngineConfig, JaxLLMEngine, SamplingParams


class _LLMStage:
    """Callable-class UDF: one engine per data-actor."""

    def __init__(self, engine_cfg: Optional[EngineConfig],
                 sampling: Optional[SamplingParams],
                 input_column: str, output_column: str):
        self.engine = JaxLLMEngine(engine_cfg or EngineConfig())
        self.sampling = sampling or SamplingParams()
        self.input_column = input_column
        self.output_column = output_column

    def __call__(self, block):
        prompts = [row[self.input_column] for row in block]
        outputs = self.engine.generate(prompts, self.sampling)
        return [
            {**row, self.output_column: out["text"]}
            for row, out in zip(block, outputs)
        ]


def build_llm_processor(
    engine_cfg: Optional[EngineConfig] = None,
    sampling: Optional[SamplingParams] = None,
    *,
    input_column: str = "prompt",
    output_column: str = "generated",
    concurrency: int = 1,
    num_tpus: float = 0,
):
    """Returns ``fn(Dataset) -> Dataset`` adding ``output_column``."""
    from ..data import ActorPoolStrategy

    def process(dataset):
        return dataset.map_batches(
            _LLMStage,
            fn_constructor_args=(
                engine_cfg, sampling, input_column, output_column
            ),
            compute=ActorPoolStrategy(
                size=concurrency,
                num_tpus=num_tpus,
                num_cpus=1 if not num_tpus else 0,
            ),
        )

    return process
