"""Tokenizers for the LLM layer.

Default is a byte-level tokenizer (vocab 256 + BOS/EOS) — zero external
assets, works for any text, matches the tiny/self-trained GPT-2 configs.
A HuggingFace tokenizer can be dropped in via ``HFTokenizer`` when local
tokenizer files exist (no network fetch happens here).
"""

from __future__ import annotations

from typing import List


class ByteTokenizer:
    """UTF-8 bytes + BOS(256)/EOS(257); vocab_size 258 (pad to lanes in the
    model config)."""

    BOS = 256
    EOS = 257

    vocab_size = 258

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] + ids) if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Wrap a locally available HuggingFace tokenizer (no downloads)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = self._tok.vocab_size
        self.EOS = self._tok.eos_token_id

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids)
