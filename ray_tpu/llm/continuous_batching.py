"""Continuous-batching decode scheduler: one resident decode loop per
replica, admission and retirement at token boundaries.

Reference: ray ``llm/_internal/serve/serving_patterns/prefill_decode/``
decode replicas + the Orca insight (iteration-level scheduling): the
decode loop never drains to admit work — new sequences join the running
batch between decode steps, finished ones leave, and the chip stays at
full duty regardless of per-request lengths.  This is the subsystem the
``JaxLLMEngine`` slot pool approximates caller-side (every ``run()``
caller steps the shared engine under a lock); here ONE owner thread
steps, callers only enqueue and consume, so a replica's decode cadence
is independent of how many clients are connected.

TPU-native shape decisions:

  - **Padded-to-bucket batches.**  The physical KV cache is sized to the
    smallest power-of-two bucket that holds the active set, so decode
    compute scales with occupancy instead of always paying
    ``max_batch_size``.  XLA programs are compiled per bucket — decode,
    row splice, row move, and adjacent-bucket grow/shrink — which bounds
    total compiles at ``O(log2(max_batch_size))`` per program kind.
    Growth is immediate (demand present), shrink waits out
    ``shrink_patience`` consecutive low-occupancy steps so occupancy
    jitter cannot thrash reallocation.  Greedy outputs are
    token-parity-exact across bucket shapes (pinned in tests; raw logits
    are NOT bitwise-stable across batch shapes — XLA vectorizes each
    shape differently — so parity is defined at the sampled-token level).
  - **Per-slot KV over the zero-copy handoff.**  Admission splices a
    prefilled ``[L, 1, H, S, D]`` KV block into a batch row with one
    jitted ``dynamic_update_slice`` — the same block that rode the
    framing-v2 out-of-band path from a prefill replica
    (``llm.disagg``), so a disaggregated admission costs one H2D splice.
  - **Starvation guard.**  Admission is FIFO; when the queue head has
    waited past ``starvation_timeout_s`` with the bucket already at
    ``max_batch_size``, the scheduler preempts the longest-running
    eligible sequence: its KV row and generation state move to host, the
    starved request takes the slot, and the preempted sequence re-enters
    at the front of the resume queue to continue from its exact KV
    (token-exact for greedy — decode state is nothing but KV + generated
    ids).  ``max_preemptions_per_seq`` bounds churn so every sequence
    keeps forward progress.
  - **Prefix KV cache.**  Prompt KV blocks are indexed by a chained
    block hash (vLLM-style); a later prompt whose FULL token sequence is
    covered re-admits straight from the cache — no prefill replica hop,
    first token sampled from the cached last-position logits (exact).
    Partial-chain matches inform routing affinity only (suffix
    prefill-at-offset is not a compiled program on decode replicas; see
    docs/llm_serving.md).

Locking contract: ``_lock`` guards queue/slot METADATA, subscriber
queues, and counters.  Jax arrays (the cache) are touched only by the
stepping thread, device work and registry round trips happen outside the
lock, and consumers wait on per-request events/queues — never on the
engine lock.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import itertools
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..models import model_family
from .engine import EngineConfig, SamplingParams, encode_prompt
from .tokenizer import ByteTokenizer


@dataclasses.dataclass
class ContinuousBatchingConfig:
    """Knobs for the resident decode scheduler (docs/llm_serving.md)."""

    # Consecutive steps with occupancy <= bucket/2 before shrinking.
    shrink_patience: int = 16
    # Queue-head wait that triggers the starvation guard (only once the
    # bucket is maxed — growth always beats preemption).
    starvation_timeout_s: float = 2.0
    # A preemption victim must have generated at least this many tokens
    # (younger sequences are about to pay their admission cost back).
    preempt_min_tokens: int = 4
    # Per-sequence preemption budget: guarantees forward progress.
    max_preemptions_per_seq: int = 2
    # Prefix KV cache budget in cached prompt TOKENS (host memory).
    prefix_cache_tokens: int = 4096
    # Tokens per hash block in the prefix-cache chain.
    prefix_block_tokens: int = 16
    # Serving-telemetry deployment tag for per-request histograms.
    deployment: str = "llm_batched"


def prefix_block_keys(token_ids: List[int], block_tokens: int) -> List[bytes]:
    """Chained block digests: key_i commits to every token in blocks
    [0, i] — two prompts share key_i iff their first (i+1) blocks match.
    Routers use these for affinity; the engine cache uses the full-prompt
    key (chain tail + ragged tail tokens) for exact reuse."""
    keys: List[bytes] = []
    prev = b""
    for i in range(0, len(token_ids) - len(token_ids) % block_tokens,
                   block_tokens):
        h = hashlib.blake2b(prev, digest_size=16)
        h.update(np.asarray(token_ids[i:i + block_tokens], np.int32).tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


def full_prompt_key(token_ids: List[int], block_tokens: int) -> bytes:
    chain = prefix_block_keys(token_ids, block_tokens)
    h = hashlib.blake2b(chain[-1] if chain else b"", digest_size=16)
    tail = len(token_ids) - len(token_ids) % block_tokens
    h.update(np.asarray(token_ids[tail:], np.int32).tobytes())
    h.update(len(token_ids).to_bytes(4, "little"))
    return h.digest()


class PrefixKVCache:
    """Host-side LRU of prompt KV blocks, keyed by chained block hashes.

    ``store`` keeps a trimmed ``[L, 1, H, prompt_len, D]`` host copy of a
    prompt's KV plus its last-position logits; ``lookup`` returns the
    entry only on FULL coverage of the new prompt's tokens (exact reuse —
    the first token re-samples from the cached logits, so even
    temperature>0 requests draw from the true distribution).  Evicts
    least-recently-used entries past the token budget.  Thread-safety is
    the caller's (engine lock)."""

    def __init__(self, max_tokens: int, block_tokens: int):
        self.max_tokens = max_tokens
        self.block_tokens = max(1, block_tokens)
        self._entries: "collections.OrderedDict[bytes, dict]" = (
            collections.OrderedDict()
        )
        self._block_index: Dict[bytes, bytes] = {}  # block key -> entry key
        self._tokens = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def build_entry(token_ids: List[int], k, v, logits,
                    block_tokens: int) -> dict:
        """Host copies for one prompt's KV (call OUTSIDE the engine lock —
        the copies are the expensive part)."""
        n = len(token_ids)
        return {
            "key": full_prompt_key(token_ids, block_tokens),
            "token_ids": list(token_ids),
            # Trim to the prompt span: the tail of the row is zeros.
            "k": np.ascontiguousarray(np.asarray(k)[:, :, :, :n]),
            "v": np.ascontiguousarray(np.asarray(v)[:, :, :, :n]),
            "logits": np.asarray(logits, np.float32).reshape(-1),
            "blocks": prefix_block_keys(token_ids, block_tokens),
        }

    def insert(self, entry: dict) -> None:
        if self.max_tokens <= 0 or not entry["token_ids"]:
            return
        key = entry["key"]
        if key in self._entries:
            self._entries.move_to_end(key)
            return
        self._entries[key] = entry
        for bk in entry["blocks"]:
            self._block_index[bk] = key
        self._tokens += len(entry["token_ids"])
        while self._tokens > self.max_tokens and len(self._entries) > 1:
            _, old = self._entries.popitem(last=False)
            self._tokens -= len(old["token_ids"])
            for bk in old["blocks"]:
                if self._block_index.get(bk) == old["key"]:
                    del self._block_index[bk]

    def contains(self, key: bytes) -> bool:
        """Key-presence check without LRU touch or hit/miss accounting
        (dedupe probe on the store path)."""
        return key in self._entries

    def lookup(self, token_ids: List[int]) -> Optional[dict]:
        key = full_prompt_key(token_ids, self.block_tokens)
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
            self.hits += 1
            return entry
        self.misses += 1
        return None

    def match_depth(self, token_ids: List[int]) -> int:
        """Longest cached block-chain prefix, in blocks (routing signal)."""
        depth = 0
        for bk in prefix_block_keys(token_ids, self.block_tokens):
            if bk not in self._block_index:
                break
            depth += 1
        return depth

    def stats(self) -> Dict[str, int]:
        return {
            "entries": len(self._entries),
            "tokens": self._tokens,
            "hits": self.hits,
            "misses": self.misses,
        }


@dataclasses.dataclass
class _Seq:
    rid: int
    prompt_len: int
    generated: List[int]
    params: SamplingParams
    enq_t: float
    admit_t: float = 0.0
    first_t: float = 0.0
    last_t: float = 0.0
    gaps: List[float] = dataclasses.field(default_factory=list)
    done: bool = False
    cancelled: bool = False
    preemptions: int = 0

    @property
    def last_pos(self) -> int:
        return self.prompt_len + len(self.generated) - 1


def _buckets(max_batch: int) -> List[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


class ContinuousBatchingEngine:
    """Decode-role engine with a resident batched decode loop.

    Callers enqueue (``submit_kv`` / ``submit_cached``) and consume
    (``stream`` / ``result``); the owner thread (started by ``start()``)
    runs ``step()`` — retire, starvation guard, admit, one decode — at
    every token boundary."""

    def __init__(self, cfg: Optional[EngineConfig] = None,
                 cb: Optional[ContinuousBatchingConfig] = None,
                 tokenizer=None):
        import jax

        from ray_tpu.util.debug_locks import make_condition

        self.cfg = cfg or EngineConfig()
        self.cb = cb or ContinuousBatchingConfig()
        self.tokenizer = tokenizer or ByteTokenizer()
        mcfg = self.cfg.model
        fam = model_family(mcfg)
        self.family = fam
        if self.cfg.param_loader is not None:
            self.params = self.cfg.param_loader()
        else:
            self.params = fam.init(jax.random.PRNGKey(self.cfg.seed), mcfg)
        self._key = jax.random.PRNGKey(self.cfg.seed + 1)
        self._buckets = _buckets(self.cfg.max_batch_size)
        self.bucket = self._buckets[0]
        self.cache = fam.init_cache(mcfg, self.bucket, self.cfg.max_seq_len)
        self.slots: List[Optional[_Seq]] = [None] * self.bucket

        # Compiled-program caches, all keyed by bucket (bounded at
        # O(log2 max_batch) compiles per kind — the recompile contract).
        self._decode_fns: Dict[int, Any] = {}
        self._insert_fns: Dict[int, Any] = {}
        self._move_fns: Dict[int, Any] = {}
        self._resize_fns: Dict[Tuple[int, int], Any] = {}
        from ..models.gpt2_decode import sample_logits

        self._sample = jax.jit(
            sample_logits, static_argnames=("temperature", "top_k", "top_p")
        )

        self._cond = make_condition("llm.cb.scheduler")
        self._lock = self._cond  # the condition IS the engine lock
        self._next_id = itertools.count()
        # Pending admissions: (rid, meta, k_host, v_host).  Preempted
        # sequences go on _resume (drained before _waiting — they already
        # waited once), except that a starvation-guard preemption hands
        # its freed slot to the starved _waiting head first.
        self._waiting: "collections.deque" = collections.deque()
        self._resume: "collections.deque" = collections.deque()
        self._admit_waiting_first = False
        self._finished: Dict[int, dict] = {}
        self._subs: Dict[int, _queue.SimpleQueue] = {}
        self._events: Dict[int, threading.Event] = {}
        self.prefix_cache = PrefixKVCache(
            self.cb.prefix_cache_tokens, self.cb.prefix_block_tokens
        )
        self._starved_since: Optional[float] = None
        self._low_occupancy_steps = 0
        # Cumulative accounting (stats() + flight-recorder deltas).
        self.counters = {
            "admitted": 0, "retired": 0, "preempted": 0, "steps": 0,
            "max_occupancy": 0,
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fail_count = 0
        self._dead = False

    # ------------------------------------------------------------ programs
    def _decode_fn(self, b: int):
        fn = self._decode_fns.get(b)
        if fn is None:
            import jax

            fam, mcfg = self.family, self.cfg.model
            fn = jax.jit(
                lambda params, cache, tokens, pos: fam.decode_step(
                    params, tokens, pos, cache, mcfg
                ),
                donate_argnums=(1,),
            )
            self._decode_fns[b] = fn
        return fn

    def _insert_fn(self, b: int):
        fn = self._insert_fns.get(b)
        if fn is None:
            import jax

            def insert(cache, k1, v1, idx):
                return {
                    "k": jax.lax.dynamic_update_slice(
                        cache["k"], k1, (0, idx, 0, 0, 0)
                    ),
                    "v": jax.lax.dynamic_update_slice(
                        cache["v"], v1, (0, idx, 0, 0, 0)
                    ),
                }

            fn = jax.jit(insert, donate_argnums=(0,))
            self._insert_fns[b] = fn
        return fn

    def _move_fn(self, b: int):
        fn = self._move_fns.get(b)
        if fn is None:
            import jax

            def move(cache, src, dst):
                # Row shape from the traced operand ([L, b, H, S, D] —
                # static at trace time), NOT from engine state: this fn is
                # keyed by bucket and may be compiled ahead of use.
                shape = cache["k"].shape
                row = (shape[0], 1) + tuple(shape[2:])
                k1 = jax.lax.dynamic_slice(cache["k"], (0, src, 0, 0, 0), row)
                v1 = jax.lax.dynamic_slice(cache["v"], (0, src, 0, 0, 0), row)
                return {
                    "k": jax.lax.dynamic_update_slice(
                        cache["k"], k1, (0, dst, 0, 0, 0)
                    ),
                    "v": jax.lax.dynamic_update_slice(
                        cache["v"], v1, (0, dst, 0, 0, 0)
                    ),
                }

            fn = jax.jit(move, donate_argnums=(0,))
            self._move_fns[b] = fn
        return fn

    def _resize_fn(self, old: int, new: int):
        fn = self._resize_fns.get((old, new))
        if fn is None:
            import jax
            import jax.numpy as jnp

            fam, mcfg = self.family, self.cfg.model
            max_len = self.cfg.max_seq_len

            if new > old:
                def resize(cache):
                    fresh = fam.init_cache(mcfg, new, max_len)
                    return {
                        "k": jax.lax.dynamic_update_slice(
                            fresh["k"], cache["k"], (0, 0, 0, 0, 0)
                        ),
                        "v": jax.lax.dynamic_update_slice(
                            fresh["v"], cache["v"], (0, 0, 0, 0, 0)
                        ),
                    }
            else:
                def resize(cache):
                    return {
                        "k": jnp.asarray(cache["k"][:, :new]),
                        "v": jnp.asarray(cache["v"][:, :new]),
                    }

            # No donation: the output shape differs from the input's, so
            # XLA cannot reuse the buffer (donating only warns).
            fn = jax.jit(resize)
            self._resize_fns[(old, new)] = fn
        return fn

    def compile_buckets(self) -> None:
        """Compile every bucket's programs up front (insert, decode, row
        move, adjacent grow/shrink) against throwaway caches, so no jit
        compile can land inside serving and masquerade as a multi-second
        inter-token stall.  Touches only the compiled-fn caches — safe
        to call while the loop runs (worst case both threads compile the
        same key once)."""
        import jax.numpy as jnp

        fam, mcfg = self.family, self.cfg.model
        max_len = self.cfg.max_seq_len
        row = None
        for i, b in enumerate(self._buckets):
            cache = fam.init_cache(mcfg, b, max_len)
            if row is None:
                one = fam.init_cache(mcfg, 1, max_len)
                row = (one["k"], one["v"])
            cache = self._insert_fn(b)(cache, row[0], row[1], 0)
            zeros = jnp.zeros(b, jnp.int32)
            _, cache = self._decode_fn(b)(self.params, cache, zeros, zeros)
            self._move_fn(b)(cache, 0, 0)
            if i + 1 < len(self._buckets):
                nb = self._buckets[i + 1]
                grown = self._resize_fn(b, nb)(
                    fam.init_cache(mcfg, b, max_len)
                )
                self._resize_fn(nb, b)(grown)

    # ----------------------------------------------------------- admission
    def submit_kv(self, meta: Dict[str, Any], k, v) -> int:
        """Enqueue a prefilled request (disaggregated admission).  ``meta``
        carries prompt_len / first_token / sampling / logits / token_ids
        (see llm.disagg.PrefillEngine.prefill); ``k``/``v`` are the
        [L, 1, H, S, D] prompt KV pages (device or host).  Also feeds the
        prefix cache so future identical prompts skip prefill."""
        if self._dead:
            raise RuntimeError("decode engine failed; replica is dead")
        kh = np.asarray(k)
        vh = np.asarray(v)
        token_ids = meta.get("token_ids")
        entry = None
        if token_ids and meta.get("logits") is not None:
            # Cheap key check before the expensive host copies: a repeat
            # prompt arriving via the prefill path (affinity re-home,
            # evicted router entry) is already cached and build_entry's
            # full-KV copies would be discarded by insert()'s dedupe.
            key = full_prompt_key(token_ids, self.cb.prefix_block_tokens)
            with self._lock:
                known = self.prefix_cache.contains(key)
            if not known:
                entry = PrefixKVCache.build_entry(
                    token_ids, kh, vh, meta["logits"],
                    self.cb.prefix_block_tokens,
                )
        with self._lock:
            rid = next(self._next_id)
            if entry is not None:
                self.prefix_cache.insert(entry)
            self._enqueue_locked(rid, dict(meta), kh, vh)
            return rid

    def submit_cached(self, prompt: str,
                      params: Optional[SamplingParams] = None
                      ) -> Optional[int]:
        """Prefix-cache admission: if the prompt's full token sequence is
        cached, enqueue straight from the cached KV (no prefill anywhere)
        and return a rid; else None (caller falls back to a prefill
        replica — and the miss is accounted)."""
        if self._dead:
            raise RuntimeError("decode engine failed; replica is dead")
        params = params or SamplingParams()
        token_ids = encode_prompt(
            self.tokenizer, prompt, self.cfg.max_seq_len
        )
        from ray_tpu.util import flight_recorder

        with self._lock:
            cached = self.prefix_cache.lookup(token_ids)
            if cached is not None:
                logits = cached["logits"]
                kc, vc = cached["k"], cached["v"]
        flight_recorder.record_llm_prefix_lookup("engine", cached is not None)
        if cached is None:
            return None
        # Row assembly outside the lock.  The first token is NOT sampled
        # here: sampling may split the engine PRNG key, which belongs to
        # the stepping thread alone (a caller-thread split would race
        # _decode_once and hand two requests the same subkey) — the
        # admission path samples from the cached logits at the token
        # boundary instead (meta carries them).
        n = len(token_ids)
        shape = list(kc.shape)
        shape[3] = self.cfg.max_seq_len
        k = np.zeros(shape, kc.dtype)
        v = np.zeros(shape, vc.dtype)
        k[:, :, :, :n] = kc
        v[:, :, :, :n] = vc
        meta = {
            "prompt_len": n,
            "first_logits": logits,
            "sampling": params,
            "token_ids": token_ids,
        }
        with self._lock:
            rid = next(self._next_id)
            self._enqueue_locked(rid, meta, k, v)
            return rid

    def _enqueue_locked(self, rid: int, meta: dict, k, v) -> None:
        meta.setdefault("enq_t", time.monotonic())
        self._waiting.append((rid, meta, k, v))
        self._subs.setdefault(rid, _queue.SimpleQueue())
        self._events.setdefault(rid, threading.Event())
        self._cond.notify_all()

    def prefix_match_depth(self, prompt: str) -> int:
        token_ids = encode_prompt(self.tokenizer, prompt, self.cfg.max_seq_len)
        with self._lock:
            return self.prefix_cache.match_depth(token_ids)

    def _sample_host(self, logits: np.ndarray, params: SamplingParams):
        """Sample next token(s) from host logits.  Greedy is a pure
        argmax (no PRNG consumed — batch composition can't perturb the
        key stream, the parity contract); stochastic params go through
        the jitted sampler with a fresh subkey.  Called only from the
        stepping thread (the PRNG key is unguarded by design)."""
        if params.temperature == 0.0:
            return np.argmax(logits, axis=-1)
        import jax

        self._key, sub = jax.random.split(self._key)
        return np.asarray(
            self._sample(
                logits, sub, temperature=params.temperature,
                top_k=params.top_k, top_p=params.top_p,
            )
        )

    # ----------------------------------------------------- lifecycle/loop
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="llm-cb-decode", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        with self._lock:
            self._cond.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                has_work = (
                    self._waiting or self._resume
                    or any(s is not None for s in self.slots)
                )
                if not has_work:
                    # Bounded idle wait (RTL006); woken by submissions.
                    self._cond.wait(timeout=0.05)
                    continue
            try:
                self.step()
            except Exception:  # noqa: BLE001 — fail every waiter, loudly
                import logging
                import traceback

                logging.getLogger(__name__).error(
                    "continuous-batching step failed:\n%s",
                    traceback.format_exc(),
                )
                self._fail_all()

    def _fail_all(self) -> None:
        with self._lock:
            seqs = [s for s in self.slots if s is not None]
            pend = list(self._resume) + list(self._waiting)
            self._resume.clear()
            self._waiting.clear()
            for i in range(len(self.slots)):
                self.slots[i] = None
            for s in seqs:
                self._finish_locked(s, error="decode loop failed")
            for rid, _meta, _k, _v in pend:
                self._finish_rid_locked(rid, error="decode loop failed")
            retired = seqs
        for s in retired:
            self._record_request(s, outcome="error")
        # Recover device state: a failure inside a DONATING jitted call
        # (decode/insert/move) may have invalidated self.cache even
        # though the assignment never landed — without reinit every
        # later step fails against the dead buffer and the replica
        # black-holes requests forever.  Repeated failures mark the
        # engine dead instead (crash-loop: surface, don't mask).
        self._fail_count += 1
        if self._fail_count >= 3:
            self._dead = True
            self._stop.set()
            return
        try:
            fresh = self.family.init_cache(
                self.cfg.model, self._buckets[0], self.cfg.max_seq_len
            )
            with self._lock:
                self.bucket = self._buckets[0]
                self.slots = [None] * self.bucket
                self._low_occupancy_steps = 0
            self.cache = fresh
        except Exception:  # noqa: BLE001 — can't recover: go dead
            self._dead = True
            self._stop.set()

    @property
    def healthy(self) -> bool:
        return not self._dead

    # ----------------------------------------------------------- stepping
    def step(self) -> None:
        """One token boundary + one decode step for the active set."""
        admitted, retired = self._token_boundary()
        active = self._decode_once()
        with self._lock:
            occupancy = sum(1 for s in self.slots if s is not None)
            queue_depth = len(self._waiting) + len(self._resume)
            self.counters["steps"] += 1
            self.counters["max_occupancy"] = max(
                self.counters["max_occupancy"], active
            )
            if active and active * 2 <= self.bucket:
                self._low_occupancy_steps += 1
            else:
                self._low_occupancy_steps = 0
        from ray_tpu.util import flight_recorder

        flight_recorder.record_llm_step(
            occupancy=occupancy, queue_depth=queue_depth,
            admitted=admitted, retired=retired, bucket=self.bucket,
        )
        self._maybe_shrink()

    def _token_boundary(self) -> Tuple[int, int]:
        """Retire finished, run the starvation guard, admit waiters.
        Returns (admissions, retirements)."""
        retired = self._retire()
        self._starvation_guard()
        return self._admit(), retired

    def _retire(self) -> int:
        with self._lock:
            done = [
                (i, s) for i, s in enumerate(self.slots)
                if s is not None and (s.done or s.cancelled)
            ]
            for i, s in done:
                self.slots[i] = None
                if not s.cancelled:
                    self._finish_locked(s)
                    self.counters["retired"] += 1
                else:
                    self._finish_rid_locked(s.rid, cancelled=True)
        # Histograms outside the engine lock (registry has its own).
        retired = 0
        for _, s in done:
            if not s.cancelled:
                retired += 1
                self._record_request(s, outcome="ok")
        return retired

    def _finish_locked(self, s: _Seq, error: Optional[str] = None) -> None:
        if s.rid not in self._subs and s.rid not in self._events:
            return  # consumer already released; storing would leak
        gen = s.generated
        stop = (
            s.params.stop_token if s.params.stop_token is not None
            else getattr(self.tokenizer, "EOS", None)
        )
        if stop is not None and gen and gen[-1] == stop:
            gen = gen[:-1]
        result = {
            "request_id": s.rid,
            "token_ids": gen,
            "text": self.tokenizer.decode(gen),
            "num_generated": len(s.generated),
        }
        if error:
            result["error"] = error
        self._finished[s.rid] = result
        q = self._subs.get(s.rid)
        if q is not None:
            q.put(None)  # stream sentinel
        ev = self._events.get(s.rid)
        if ev is not None:
            ev.set()

    def _finish_rid_locked(self, rid: int, error: Optional[str] = None,
                           cancelled: bool = False) -> None:
        if cancelled and rid not in self._subs and rid not in self._events:
            return  # consumer already released; storing would leak
        result = {"request_id": rid, "token_ids": [], "text": "",
                  "num_generated": 0}
        if error:
            result["error"] = error
        if cancelled:
            result["cancelled"] = True
        self._finished[rid] = result
        q = self._subs.get(rid)
        if q is not None:
            q.put(None)
        ev = self._events.get(rid)
        if ev is not None:
            ev.set()

    def _record_request(self, s: _Seq, outcome: str) -> None:
        """Per-request serving telemetry (PR-10 histograms): queue wait =
        enqueue→admission, TTFT = enqueue→first token, plus every
        inter-token gap — recorded engine-side so thousands of queued
        clients need no consumer thread each to be measured."""
        from ray_tpu.util import flight_recorder

        try:
            flight_recorder.record_serve_stream(
                self.cb.deployment, "engine",
                max(0.0, s.admit_t - s.enq_t),
                max(0.0, (s.first_t or s.admit_t) - s.enq_t),
                s.gaps, outcome=outcome,
            )
        except Exception:  # raylint: waive[RTL003] telemetry must not fail retirement
            pass

    def _starvation_guard(self) -> None:
        with self._lock:
            if not self._waiting and not self._resume:
                self._starved_since = None
                return
            free = any(s is None for s in self.slots)
            if free or self.bucket < self.cfg.max_batch_size:
                self._starved_since = None
                return
            now = time.monotonic()
            if self._starved_since is None:
                self._starved_since = now
                return
            if now - self._starved_since < self.cb.starvation_timeout_s:
                return
            victims = [
                (len(s.generated), i, s)
                for i, s in enumerate(self.slots)
                if s is not None and not s.done and not s.cancelled
                and len(s.generated) >= self.cb.preempt_min_tokens
                and s.preemptions < self.cb.max_preemptions_per_seq
            ]
            if not victims:
                self._starved_since = now  # re-arm; nothing eligible yet
                return
            _, idx, victim = max(victims, key=lambda t: (t[0], -t[1]))
            self.slots[idx] = None
            self._starved_since = None
            victim.preemptions += 1
            self.counters["preempted"] += 1
        # KV extraction outside the lock: one D2H of the victim's row.
        kh = np.asarray(self.cache["k"][:, idx:idx + 1])
        vh = np.asarray(self.cache["v"][:, idx:idx + 1])
        meta = {
            "prompt_len": victim.prompt_len,
            "sampling": victim.params,
            "resume_seq": victim,
        }
        with self._lock:
            self._resume.appendleft((victim.rid, meta, kh, vh))
            # The freed slot belongs to the starved head, not the victim.
            self._admit_waiting_first = True
        from ray_tpu.util import flight_recorder

        flight_recorder.record_llm_preemption()

    def _admit(self) -> int:
        """Drain pending admissions into free slots, growing the bucket
        (adjacent steps) while demand remains.  Splices happen outside
        the lock; slot metadata commits under it."""
        admitted = 0
        while True:
            with self._lock:
                pending = len(self._waiting) + len(self._resume)
                if pending == 0:
                    return admitted
                idx = next(
                    (i for i, s in enumerate(self.slots) if s is None), None
                )
                if idx is None and self.bucket >= self.cfg.max_batch_size:
                    return admitted
                entry = None
                if idx is not None:
                    if self._admit_waiting_first and self._waiting:
                        source = self._waiting
                    else:
                        source = self._resume if self._resume else self._waiting
                    self._admit_waiting_first = False
                    entry = source.popleft()
                    rid = entry[0]
                    if rid in self._finished:  # cancelled while queued
                        continue
            if entry is None:
                self._grow()
                continue
            rid, meta, kh, vh = entry
            import jax.numpy as jnp

            self.cache = self._insert_fn(self.bucket)(
                self.cache, jnp.asarray(kh), jnp.asarray(vh), idx
            )
            first = meta.get("first_token")
            if first is None and meta.get("resume_seq") is None:
                # Prefix-cache admission: the first token is sampled HERE
                # (stepping thread — the only legal owner of the PRNG
                # key) from the cached last-position logits.
                first = int(
                    self._sample_host(
                        np.asarray(meta["first_logits"])[None],
                        meta["sampling"],
                    )[0]
                )
            now = time.monotonic()
            with self._lock:
                if rid in self._finished or (
                    rid not in self._subs and rid not in self._events
                ):
                    # Cancelled/released while we were splicing (the
                    # unlocked window can be long on a cold bucket):
                    # don't commit the slot — the spliced row is garbage
                    # in a FREE slot, overwritten by the next admission.
                    continue
                seq = meta.get("resume_seq")
                if seq is None:
                    seq = _Seq(
                        rid=rid,
                        prompt_len=meta["prompt_len"],
                        generated=[first],
                        params=meta["sampling"],
                        enq_t=meta.get("enq_t", now),
                        admit_t=now,
                        first_t=now,
                        last_t=now,
                    )
                    self.counters["admitted"] += 1
                    self._push_delta_locked(seq, [first])
                    self._check_done_locked(seq)
                self.slots[idx] = seq
                admitted += 1

    def _grow(self) -> None:
        new = self._buckets[self._buckets.index(self.bucket) + 1]
        self.cache = self._resize_fn(self.bucket, new)(self.cache)
        with self._lock:
            self.slots.extend([None] * (new - self.bucket))
            self.bucket = new

    def _maybe_shrink(self) -> None:
        with self._lock:
            if self.bucket == self._buckets[0]:
                return
            if self._low_occupancy_steps < self.cb.shrink_patience:
                return
            old = self.bucket
            new = self._buckets[self._buckets.index(old) - 1]
            # Plan compaction: every OCCUPIED slot >= new moves to a free
            # low slot.  The low-occupancy trigger counts decoding
            # sequences, but slots can also hold cancelled-not-yet-
            # retired sequences — if the free low slots don't cover the
            # high occupants, skip this round instead of crashing the
            # loop (the next boundary retires the cancelled ones).
            moves = []
            free_low = [i for i in range(new) if self.slots[i] is None]
            for i in range(new, old):
                if self.slots[i] is not None:
                    if not free_low:
                        self._low_occupancy_steps = 0
                        return
                    moves.append((i, free_low.pop(0)))
        for src, dst in moves:
            self.cache = self._move_fn(old)(self.cache, src, dst)
        with self._lock:
            for src, dst in moves:
                self.slots[dst] = self.slots[src]
                self.slots[src] = None
        self.cache = self._resize_fn(old, new)(self.cache)
        with self._lock:
            self.slots = self.slots[:new]
            self.bucket = new
            self._low_occupancy_steps = 0

    def _decode_once(self) -> int:
        import jax.numpy as jnp

        with self._lock:
            active = [
                (i, s) for i, s in enumerate(self.slots)
                if s is not None and not s.done and not s.cancelled
            ]
            if not active:
                return 0
            tokens = np.zeros(self.bucket, np.int32)
            pos = np.zeros(self.bucket, np.int32)
            for i, s in active:
                tokens[i] = s.generated[-1]
                pos[i] = s.last_pos
        logits, self.cache = self._decode_fn(self.bucket)(
            self.params, self.cache, jnp.asarray(tokens), jnp.asarray(pos)
        )
        logits_np = np.asarray(logits)
        # Sampling outside the lock (may hit the jitted sampler).
        sampled = [
            (i, s, int(self._sample_host(logits_np[i:i + 1], s.params)[0]))
            for i, s in active
        ]
        now = time.monotonic()
        with self._lock:
            for i, s, token in sampled:
                if self.slots[i] is not s:  # retired/preempted mid-decode
                    continue
                s.generated.append(token)
                s.gaps.append(now - s.last_t)
                s.last_t = now
                self._push_delta_locked(s, [token])
                self._check_done_locked(s)
        return len(active)

    def _push_delta_locked(self, s: _Seq, token_ids: List[int]) -> None:
        q = self._subs.get(s.rid)
        if q is not None:
            q.put(list(token_ids))

    def _check_done_locked(self, s: _Seq) -> None:
        stop = (
            s.params.stop_token if s.params.stop_token is not None
            else getattr(self.tokenizer, "EOS", None)
        )
        token = s.generated[-1]
        total_len = s.prompt_len + len(s.generated)
        if (
            (stop is not None and token == stop)
            or len(s.generated) >= s.params.max_tokens
            or total_len >= self.cfg.max_seq_len - 1
        ):
            s.done = True

    # --------------------------------------------------------- consumption
    def result(self, rid: int, timeout_s: float = 300.0) -> dict:
        ev = self._events.get(rid)
        if ev is None:
            with self._lock:
                done = self._finished.pop(rid, None)
            if done is not None:
                return done
            raise KeyError(f"unknown request {rid}")
        if not ev.wait(timeout=timeout_s):
            self.cancel(rid)
            with self._lock:  # drop delivery state; nobody will consume
                self._subs.pop(rid, None)
                self._events.pop(rid, None)
                self._finished.pop(rid, None)
            raise TimeoutError(f"request {rid} timed out")
        with self._lock:
            done = self._finished.pop(rid)
            self._events.pop(rid, None)
            self._subs.pop(rid, None)
        if done.get("error"):
            raise RuntimeError(done["error"])
        return done

    def stream(self, rid: int, timeout_s: float = 300.0):
        """Yield text deltas for ``rid`` as tokens land (token-boundary
        granularity).  The consumer never steps the engine."""
        q = self._subs.get(rid)
        if q is None:
            raise KeyError(f"unknown request {rid}")
        deadline = time.monotonic() + timeout_s
        emitted = 0
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"stream of request {rid} timed out")
                try:
                    item = q.get(timeout=min(remaining, 1.0))
                except _queue.Empty:
                    continue
                if item is None:
                    with self._lock:
                        done = self._finished.get(rid, {})
                    if done.get("error"):
                        raise RuntimeError(done["error"])
                    # Flush the tail: stop-token trimming can shorten the
                    # final text vs streamed ids; emit whatever decode of
                    # the final ids adds beyond what we already sent.
                    tail = self.tokenizer.decode(
                        done.get("token_ids", [])[emitted:]
                    )
                    if tail:
                        yield tail
                    return
                emitted += len(item)
                text = self.tokenizer.decode(item)
                if text:
                    yield text
        finally:
            self._release(rid)

    def _release(self, rid: int) -> None:
        finished = False
        with self._lock:
            finished = rid in self._finished
            self._finished.pop(rid, None)
            self._subs.pop(rid, None)
            self._events.pop(rid, None)
        if not finished:
            self.cancel(rid)

    def cancel(self, rid: int) -> None:
        with self._lock:
            self._waiting = collections.deque(
                w for w in self._waiting if w[0] != rid
            )
            self._resume = collections.deque(
                w for w in self._resume if w[0] != rid
            )
            for s in self.slots:
                if s is not None and s.rid == rid:
                    s.cancelled = True  # loop frees the slot at boundary
                    return
            if rid not in self._finished:
                self._finish_rid_locked(rid, cancelled=True)

    # -------------------------------------------------------------- stats
    def has_unfinished(self) -> bool:
        with self._lock:
            return bool(self._waiting) or bool(self._resume) or any(
                s is not None for s in self.slots
            )

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            occupancy = sum(1 for s in self.slots if s is not None)
            return {
                "bucket": self.bucket,
                "occupancy": occupancy,
                "queue_depth": len(self._waiting) + len(self._resume),
                "prefix_cache": self.prefix_cache.stats(),
                **dict(self.counters),
            }


class BatchedDecodeReplica:
    """Actor-friendly decode replica over the resident scheduler — the
    continuous-batching successor of ``llm.disagg.DecodeReplica``.

    Deploy with ``max_concurrency`` > 1: ``add_from_kv``/``run``/
    ``run_stream`` calls only enqueue and wait; the owner thread decodes.
    """

    def __init__(self, engine_cfg: Optional[EngineConfig] = None,
                 cb_cfg: Optional[ContinuousBatchingConfig] = None,
                 warm: bool = False):
        self.engine = ContinuousBatchingEngine(
            engine_cfg or EngineConfig(), cb_cfg
        )
        if warm:
            self.engine.compile_buckets()
        self.engine.start()

    def warm(self) -> bool:
        """Pre-compile every bucket's programs (serving deployments call
        this once so no jit compile lands inside a live request)."""
        self.engine.compile_buckets()
        return True

    def add_from_kv(self, meta: Dict[str, Any]) -> int:
        """Fetch the KV pages from the prefill owner and enqueue (token-
        boundary admission into the running batch)."""
        from .disagg import fetch_prefill_kv

        k, v = fetch_prefill_kv(meta)
        return self.engine.submit_kv(meta, k, v)

    def try_add_cached(self, prompt: str,
                       params: Optional[SamplingParams] = None
                       ) -> Optional[int]:
        return self.engine.submit_cached(prompt, params)

    def generate_cached(self, prompt: str,
                        params: Optional[SamplingParams] = None,
                        timeout_s: float = 300.0) -> Optional[dict]:
        """Fused prefix-cache fast path: admission + completion in ONE
        actor round trip (None on a cache miss) — the hot repeat-prompt
        path costs the same RPC count as a monolithic engine call."""
        rid = self.engine.submit_cached(prompt, params)
        if rid is None:
            return None
        return self.engine.result(rid, timeout_s)

    def run_from_kv(self, meta: Dict[str, Any],
                    timeout_s: float = 300.0) -> dict:
        """Fused disaggregated admission + completion (one round trip
        instead of add_from_kv + run)."""
        from .disagg import fetch_prefill_kv

        k, v = fetch_prefill_kv(meta)
        rid = self.engine.submit_kv(meta, k, v)
        return self.engine.result(rid, timeout_s)

    def prefix_match_depth(self, prompt: str) -> int:
        return self.engine.prefix_match_depth(prompt)

    def run(self, request_id: int, timeout_s: float = 300.0) -> dict:
        return self.engine.result(request_id, timeout_s)

    def run_stream(self, request_id: int, timeout_s: float = 300.0):
        """Stream text deltas (engine records per-request TTFT/inter-token
        histograms at retirement — no double accounting here)."""
        yield from self.engine.stream(request_id, timeout_s)

    def cancel(self, request_id: int) -> None:
        self.engine.cancel(request_id)

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def health_check(self) -> bool:
        if not self.engine.healthy:
            raise RuntimeError(
                "continuous-batching engine failed repeatedly; replica "
                "needs replacement"
            )
        return True

    def close(self) -> None:
        self.engine.stop()
