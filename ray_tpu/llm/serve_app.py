"""OpenAI-compatible LLM serving on top of ``ray_tpu.serve``.

Reference: ray ``python/ray/llm/_internal/serve/core/server/`` (the
OpenAI-compatible router over vLLM deployments) and ``serve/llm``'s
``build_openai_app``.  The deployment holds one ``JaxLLMEngine`` per
replica (one chip each via ``num_tpus=1``); ``@serve.batch`` coalesces
concurrent single-prompt calls so they enter the engine's continuous batch
together.  Endpoints: ``/v1/completions`` and ``/v1/chat/completions``
via the serve HTTP proxy (the raw JSON body arrives as the call's single
argument).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

from .. import serve
from .engine import EngineConfig, JaxLLMEngine, SamplingParams


def _sampling_from_request(body: Dict[str, Any]) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(body.get("max_tokens", 64)),
        temperature=float(body.get("temperature", 0.0)),
        top_p=float(body.get("top_p", 1.0)),
    )


@serve.deployment(name="LLMServer", ray_actor_options={"num_cpus": 1})
class LLMServer:
    """One engine per replica; requests batch dynamically."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None,
                 model_name: str = "ray-tpu-gpt2"):
        self.engine = JaxLLMEngine(engine_cfg or EngineConfig())
        self.model_name = model_name

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def _generate_batch(self, requests: List[tuple]):
        """requests: [(prompt, SamplingParams)] — one engine pass serves
        them all (the engine's slot pool IS the batch).  All engine-state
        access holds the engine lock: SSE streams may be stepping the same
        engine from replica threads concurrently."""
        ids = [
            self.engine.add_request(prompt, params)
            for prompt, params in requests
        ]
        while True:
            with self.engine._step_lock:
                if all(i in self.engine._finished for i in ids):
                    return [self.engine._finished.pop(i) for i in ids]
                self.engine.step()

    async def __call__(self, body: Dict[str, Any]):
        """OpenAI completions-ish: dispatch on request shape.  With
        ``"stream": true`` the proxy calls this through the streaming path
        and SSE-frames each yielded chunk (OpenAI ``stream`` semantics)."""
        if body.get("stream") is True:
            return self.stream_chunks(body)
        if "messages" in body:
            return await self.chat(body)
        return await self.completions(body)

    def stream_chunks(self, body: Dict[str, Any]):
        """Sync generator of OpenAI-style streaming chunks (per decode
        step).  Runs on a replica thread via handle_request_streaming."""
        yield from _stream_openai_chunks(
            self.engine.generate_stream(
                _prompt_from_body(body), _sampling_from_request(body)
            ),
            body, self.model_name,
        )

    async def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        prompt = body.get("prompt", "")
        out = await self._generate_batch((prompt, _sampling_from_request(body)))
        return _unary_response(
            body, out, self.model_name, chat=False,
            prompt_tokens=len(self.engine.tokenizer.encode(prompt)),
        )

    async def chat(self, body: Dict[str, Any]) -> Dict[str, Any]:
        prompt = _prompt_from_body(body)
        out = await self._generate_batch((prompt, _sampling_from_request(body)))
        return _unary_response(
            body, out, self.model_name, chat=True,
            prompt_tokens=len(self.engine.tokenizer.encode(prompt)),
        )


@serve.deployment(name="LLMDisaggServer", ray_actor_options={"num_cpus": 0})
class LLMDisaggServer:
    """OpenAI endpoints over the disaggregated continuous-batching path.

    One replica of this deployment owns a prefill pool + a
    continuous-batching decode pool (``llm.continuous_batching.
    BatchedDecodeReplica``) and routes through ``DisaggRouter`` with
    prefix-cache-aware decode routing.  Streaming requests flow proxy →
    this replica (``serve.request.stream`` span) → prefill actor → decode
    actor, each hop inheriting the request's trace context, so one
    stitched cluster trace (returned in ``x-ray-tpu-trace-id``) covers
    the whole batched streaming request."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None,
                 model_name: str = "ray-tpu-gpt2",
                 num_prefill: int = 1, num_decode: int = 1,
                 cb_cfg=None, num_cpus_per_replica: float = 0.0):
        import ray_tpu
        from .continuous_batching import BatchedDecodeReplica
        from .disagg import DisaggRouter, PrefillReplica

        from .tokenizer import ByteTokenizer

        engine_cfg = engine_cfg or EngineConfig()
        self.model_name = model_name
        # Same default tokenizer the replica engines use — usage token
        # accounting must match the monolithic server's.
        self._tokenizer = ByteTokenizer()
        Pre = ray_tpu.remote(num_cpus=num_cpus_per_replica)(PrefillReplica)
        # max_concurrency is load-bearing: run()/run_stream() calls park
        # on per-request events while the resident loop decodes; a slot-
        # starved decode actor would serialize its clients.
        Dec = ray_tpu.remote(
            num_cpus=num_cpus_per_replica, max_concurrency=64
        )(BatchedDecodeReplica)
        self._prefill = [Pre.remote(engine_cfg) for _ in range(num_prefill)]
        self._decode = [
            Dec.remote(engine_cfg, cb_cfg) for _ in range(num_decode)
        ]
        # Fire-and-forget bucket pre-compile: on a loaded box the full
        # warm can take minutes, and blocking THIS replica's constructor
        # or health checks on it makes the serve reconciler strike out a
        # merely-compiling replica (kill → fresh children → more compile
        # load — a death spiral).  Early requests may pay an on-demand
        # bucket compile instead; the refs are kept so the work isn't
        # cancelled.
        self._warm_refs = [d.warm.remote() for d in self._decode]
        self.router = DisaggRouter(self._prefill, self._decode)

    def __call__(self, body: Dict[str, Any]):
        # Deliberately sync: the router blocks on actor round trips, so
        # the replica runs this on an executor thread (RTL005 — blocking
        # work must stay off the replica event loop); the streaming case
        # returns a sync generator the streaming path pulls on a thread.
        if body.get("stream") is True:
            return self.stream_chunks(body)
        prompt = _prompt_from_body(body)
        out = self.router.generate(prompt, _sampling_from_request(body))
        return _unary_response(
            body, out, self.model_name, chat="messages" in body,
            prompt_tokens=len(self._tokenizer.encode(prompt)),
        )

    def stream_chunks(self, body: Dict[str, Any]):
        """Sync generator of OpenAI streaming chunks over the router's
        disaggregated stream (runs on a replica thread; actor hops inside
        inherit the serve.request.stream trace context)."""
        yield from _stream_openai_chunks(
            self.router.stream(
                _prompt_from_body(body), _sampling_from_request(body)
            ),
            body, self.model_name,
        )

    def stats(self) -> Dict[str, Any]:
        import ray_tpu

        return {
            "router": {"hits": self.router.router_hits,
                       "misses": self.router.router_misses},
            "decode": [
                ray_tpu.get(d.stats.remote(), timeout=30)
                for d in self._decode
            ],
        }

    def check_health(self):
        # Deliberately does NOT round-trip to the child actors: a decode
        # replica busy with a bucket compile holds its executor for tens
        # of seconds, and a blocking probe here would convert "compiling"
        # into health strikes against THIS replica (the reconciler would
        # kill it and orphan the children).  Child failures surface as
        # request errors instead.
        return True


def _prompt_from_body(body: Dict[str, Any]) -> str:
    if "messages" in body:
        return "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in body.get("messages", [])
        ) + "\nassistant:"
    return body.get("prompt", "")


def _chunk_framer(body: Dict[str, Any], model_name: str, chat: bool):
    cid = f"{'chatcmpl' if chat else 'cmpl'}-{uuid.uuid4().hex[:12]}"
    created = int(time.time())
    obj = "chat.completion.chunk" if chat else "text_completion"

    def frame(choice):
        return {
            "id": cid,
            "object": obj,
            "created": created,
            "model": body.get("model", model_name),
            "choices": [choice],
        }

    return frame


def _stream_openai_chunks(deltas, body: Dict[str, Any], model_name: str):
    """Frame an engine/router delta stream as OpenAI streaming chunks —
    the ONE chunk shape both serve deployments emit.  The terminal
    finish_reason chunk is always yielded (OpenAI semantics), which also
    keeps the stream observable when every generated token decodes to
    empty text (the byte tokenizer drops ids outside its range) — SSE
    consumers never see a bare [DONE] with zero chunks."""
    chat = "messages" in body
    frame = _chunk_framer(body, model_name, chat)
    for delta in deltas:
        if chat:
            yield frame({"index": 0, "delta": {"content": delta},
                         "finish_reason": None})
        else:
            yield frame({"index": 0, "text": delta, "finish_reason": None})
    if chat:
        yield frame({"index": 0, "delta": {}, "finish_reason": "stop"})
    else:
        yield frame({"index": 0, "text": "", "finish_reason": "stop"})


def _unary_response(body: Dict[str, Any], out: Dict[str, Any],
                    model_name: str, chat: bool,
                    prompt_tokens: int = 0) -> Dict[str, Any]:
    usage = {
        "completion_tokens": out["num_generated"],
        "prompt_tokens": prompt_tokens,
        "total_tokens": prompt_tokens + out["num_generated"],
    }
    if chat:
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": body.get("model", model_name),
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": out["text"]},
                    "finish_reason": "stop",
                }
            ],
            "usage": usage,
        }
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:12]}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": body.get("model", model_name),
        "choices": [
            {"index": 0, "text": out["text"], "finish_reason": "stop"}
        ],
        "usage": usage,
    }


def build_disagg_openai_app(
    engine_cfg: Optional[EngineConfig] = None,
    model_name: str = "ray-tpu-gpt2",
    num_prefill: int = 1,
    num_decode: int = 1,
    cb_cfg=None,
):
    """OpenAI app over the prefill/decode + continuous-batching path;
    expose via ``serve.run`` + ``serve.start_http_proxy`` like
    ``build_openai_app`` (same ``/v1`` endpoints, ``stream: true``
    SSE included)."""
    d = LLMDisaggServer.options(route_prefix="/v1")
    return d.bind(engine_cfg, model_name, num_prefill, num_decode, cb_cfg)


def build_openai_app(
    engine_cfg: Optional[EngineConfig] = None,
    model_name: str = "ray-tpu-gpt2",
    num_replicas: int = 1,
    num_tpus: float = 0,
):
    """Build the Serve application; run with ``serve.run(app)`` and expose
    via ``serve.start_http_proxy()`` — then POST to ``/v1/completions`` or
    ``/v1/chat/completions``."""
    opts: Dict[str, Any] = {"num_cpus": 1}
    if num_tpus:
        opts = {"num_cpus": 0, "num_tpus": num_tpus}
    d = LLMServer.options(
        num_replicas=num_replicas,
        ray_actor_options=opts,
        route_prefix="/v1",
    )
    return d.bind(engine_cfg, model_name)
