"""OpenAI-compatible LLM serving on top of ``ray_tpu.serve``.

Reference: ray ``python/ray/llm/_internal/serve/core/server/`` (the
OpenAI-compatible router over vLLM deployments) and ``serve/llm``'s
``build_openai_app``.  The deployment holds one ``JaxLLMEngine`` per
replica (one chip each via ``num_tpus=1``); ``@serve.batch`` coalesces
concurrent single-prompt calls so they enter the engine's continuous batch
together.  Endpoints: ``/v1/completions`` and ``/v1/chat/completions``
via the serve HTTP proxy (the raw JSON body arrives as the call's single
argument).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

from .. import serve
from .engine import EngineConfig, JaxLLMEngine, SamplingParams


def _sampling_from_request(body: Dict[str, Any]) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(body.get("max_tokens", 64)),
        temperature=float(body.get("temperature", 0.0)),
        top_p=float(body.get("top_p", 1.0)),
    )


@serve.deployment(name="LLMServer", ray_actor_options={"num_cpus": 1})
class LLMServer:
    """One engine per replica; requests batch dynamically."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None,
                 model_name: str = "ray-tpu-gpt2"):
        self.engine = JaxLLMEngine(engine_cfg or EngineConfig())
        self.model_name = model_name

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def _generate_batch(self, requests: List[tuple]):
        """requests: [(prompt, SamplingParams)] — one engine pass serves
        them all (the engine's slot pool IS the batch).  All engine-state
        access holds the engine lock: SSE streams may be stepping the same
        engine from replica threads concurrently."""
        ids = [
            self.engine.add_request(prompt, params)
            for prompt, params in requests
        ]
        while True:
            with self.engine._step_lock:
                if all(i in self.engine._finished for i in ids):
                    return [self.engine._finished.pop(i) for i in ids]
                self.engine.step()

    async def __call__(self, body: Dict[str, Any]):
        """OpenAI completions-ish: dispatch on request shape.  With
        ``"stream": true`` the proxy calls this through the streaming path
        and SSE-frames each yielded chunk (OpenAI ``stream`` semantics)."""
        if body.get("stream") is True:
            return self.stream_chunks(body)
        if "messages" in body:
            return await self.chat(body)
        return await self.completions(body)

    def stream_chunks(self, body: Dict[str, Any]):
        """Sync generator of OpenAI-style streaming chunks (per decode
        step).  Runs on a replica thread via handle_request_streaming."""
        chat = "messages" in body
        if chat:
            prompt = "\n".join(
                f"{m.get('role', 'user')}: {m.get('content', '')}"
                for m in body.get("messages", [])
            ) + "\nassistant:"
        else:
            prompt = body.get("prompt", "")
        cid = f"{'chatcmpl' if chat else 'cmpl'}-{uuid.uuid4().hex[:12]}"
        created = int(time.time())
        obj = "chat.completion.chunk" if chat else "text_completion"

        def frame(choice):
            return {
                "id": cid,
                "object": obj,
                "created": created,
                "model": body.get("model", self.model_name),
                "choices": [choice],
            }

        for delta in self.engine.generate_stream(
            prompt, _sampling_from_request(body)
        ):
            if chat:
                choice = {"index": 0, "delta": {"content": delta},
                          "finish_reason": None}
            else:
                choice = {"index": 0, "text": delta, "finish_reason": None}
            yield frame(choice)
        # Terminal chunk, always emitted (OpenAI semantics: the stream ends
        # with an explicit finish_reason).  This also makes the stream
        # observable when every generated token decodes to empty text (the
        # byte tokenizer drops ids outside its range), so SSE consumers —
        # and the tier-1 test — never see a bare [DONE] with zero chunks.
        if chat:
            yield frame({"index": 0, "delta": {}, "finish_reason": "stop"})
        else:
            yield frame({"index": 0, "text": "", "finish_reason": "stop"})

    async def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        prompt = body.get("prompt", "")
        out = await self._generate_batch((prompt, _sampling_from_request(body)))
        return {
            "id": f"cmpl-{uuid.uuid4().hex[:12]}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": body.get("model", self.model_name),
            "choices": [
                {
                    "index": 0,
                    "text": out["text"],
                    "finish_reason": "stop",
                }
            ],
            "usage": {
                "completion_tokens": out["num_generated"],
                "prompt_tokens": len(self.engine.tokenizer.encode(prompt)),
                "total_tokens": (
                    len(self.engine.tokenizer.encode(prompt))
                    + out["num_generated"]
                ),
            },
        }

    async def chat(self, body: Dict[str, Any]) -> Dict[str, Any]:
        messages = body.get("messages", [])
        prompt = "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in messages
        ) + "\nassistant:"
        out = await self._generate_batch((prompt, _sampling_from_request(body)))
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": body.get("model", self.model_name),
            "choices": [
                {
                    "index": 0,
                    "message": {
                        "role": "assistant",
                        "content": out["text"],
                    },
                    "finish_reason": "stop",
                }
            ],
            "usage": {"completion_tokens": out["num_generated"]},
        }


def build_openai_app(
    engine_cfg: Optional[EngineConfig] = None,
    model_name: str = "ray-tpu-gpt2",
    num_replicas: int = 1,
    num_tpus: float = 0,
):
    """Build the Serve application; run with ``serve.run(app)`` and expose
    via ``serve.start_http_proxy()`` — then POST to ``/v1/completions`` or
    ``/v1/chat/completions``."""
    opts: Dict[str, Any] = {"num_cpus": 1}
    if num_tpus:
        opts = {"num_cpus": 0, "num_tpus": num_tpus}
    d = LLMServer.options(
        num_replicas=num_replicas,
        ray_actor_options=opts,
        route_prefix="/v1",
    )
    return d.bind(engine_cfg, model_name)
