"""Prefill/decode disaggregated serving.

Reference: ray ``llm/_internal/serve/serving_patterns/prefill_decode/`` +
``engines/vllm/kv_transfer/`` — prefill replicas compute the prompt's KV
cache, decode replicas continue token generation, and the KV pages move
replica-to-replica without re-running the prompt.

TPU-native shape: the KV transfer rides the device-object plane
(``ray_tpu.collective.device_objects``) — the prefill replica keeps the
[L, 1, H, S, D] KV blocks resident and returns ``DeviceRef`` metadata;
the decode replica fetches point-to-point from the owner (ICI/DCN-safe:
same-process hits HBM directly, cross-process streams over the owner's
RPC channel) and splices the pages into its batch cache with one jitted
``dynamic_update_slice``.  The cross-process hop is zero-copy end to
end: ``device_fetch`` replies frame the KV block's host view as an
out-of-band buffer segment (no ``tobytes()`` flat copy — see
``core_worker.handle_device_fetch`` / docs/performance.md) and the
decode side rebuilds with ``np.frombuffer`` straight from the receive
buffer, so a KV handoff costs exactly one D2H and one H2D.  Compute stays in exactly two XLA programs per
replica role: prefill compiles only the prefill graph, decode only the
decode-step graph — each role's chip runs one static-shape program at
100% duty instead of interleaving both.

Why disaggregate (same motivation as the reference): prefill is
compute-bound and bursty, decode is HBM-bound and steady; separating them
lets each pool scale independently and keeps long prompts from stalling
token streams of in-flight requests.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..models import model_family
from ..models.gpt2_decode import sample_logits
from .engine import EngineConfig, JaxLLMEngine, SamplingParams
from .tokenizer import ByteTokenizer


class PrefillEngine:
    """Prefill-only engine: prompt -> (first token, resident KV pages).

    No batch slots, no decode program — one jitted prefill over a
    single-row cache; the row is published to the device-object store and
    ownership transfers to the fetching decode replica.
    """

    def __init__(self, cfg: EngineConfig, tokenizer=None):
        import jax

        self.cfg = cfg
        self.tokenizer = tokenizer or ByteTokenizer()
        mcfg = cfg.model
        fam = model_family(mcfg)
        self.family = fam
        if cfg.param_loader is not None:
            self.params = cfg.param_loader()
        else:
            self.params = fam.init(jax.random.PRNGKey(cfg.seed), mcfg)
        self._key = jax.random.PRNGKey(cfg.seed + 1)

        def prefill_row(params, tokens, length):
            import jax.numpy as jnp

            cache = fam.init_cache(mcfg, 1, cfg.max_seq_len)
            logits, cache = fam.prefill(
                params, tokens[None], jnp.asarray([length]), cache, mcfg
            )
            return logits[0], cache

        self._prefill_row = jax.jit(prefill_row)
        self._sample = jax.jit(
            sample_logits, static_argnames=("temperature", "top_k", "top_p")
        )

    def prefill(
        self, prompt: str, params: Optional[SamplingParams] = None
    ) -> Dict[str, Any]:
        """Run the prompt; return picklable metadata + KV DeviceRefs.

        The caller (router) hands the dict to a decode replica, which
        fetches and frees the refs — the KV pages live on this replica
        only until that single consumer collects them.
        """
        import jax

        from ..collective.device_objects import device_object_store

        from .engine import encode_prompt

        params = params or SamplingParams()
        token_ids = encode_prompt(self.tokenizer, prompt, self.cfg.max_seq_len)
        tokens = np.zeros(self.cfg.max_seq_len, np.int32)
        tokens[: len(token_ids)] = token_ids
        import jax.numpy as jnp

        logits, cache = self._prefill_row(
            self.params, jnp.asarray(tokens), len(token_ids)
        )
        self._key, sub = jax.random.split(self._key)
        first = int(
            np.asarray(
                self._sample(
                    logits[None], sub,
                    temperature=params.temperature,
                    top_k=params.top_k,
                    top_p=params.top_p,
                )
            )[0]
        )
        store = device_object_store()
        return {
            "prompt_len": len(token_ids),
            "first_token": first,
            "sampling": params,
            "k_ref": store.put(cache["k"]),
            "v_ref": store.put(cache["v"]),
        }


class DecodeReplica:
    """Decode-role replica: adopts prefilled KV, streams decode steps.

    Wraps the standard engine (whose ``add_request_from_kv`` owns the
    disaggregated admission path); the prefill program is simply never
    compiled or run on this replica — all admissions arrive as KV pages."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None):
        self.engine = JaxLLMEngine(engine_cfg or EngineConfig())

    def add_from_kv(self, meta: Dict[str, Any]) -> int:
        """Fetch the KV pages from the prefill owner and enqueue."""
        from ..collective.device_objects import device_object_store

        store = device_object_store()
        k = store.fetch(meta["k_ref"])
        v = store.fetch(meta["v_ref"])
        store.free(meta["k_ref"])
        store.free(meta["v_ref"])
        return self.engine.add_request_from_kv(meta, k, v)

    def run(self, request_id: int, timeout_s: float = 300.0) -> dict:
        """Decode until this request finishes; returns its result.

        Deploy decode replicas with ``max_concurrency`` > 1: run() loops
        step the shared engine, and concurrent add_from_kv admissions
        (arriving on other lanes) join the SAME decode batch — on an
        exclusive actor each request would decode solo, which is the
        anti-pattern disaggregation exists to avoid."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self.engine._step_lock:
                done = self.engine._finished.pop(request_id, None)
                if done is None:
                    self.engine.step()
                    done = self.engine._finished.pop(request_id, None)
            if done is not None:
                return done
            if time.monotonic() > deadline:
                self.engine.cancel_request(request_id)
                raise TimeoutError(f"decode of request {request_id} timed out")

    def run_stream(self, request_id: int, timeout_s: float = 300.0):
        """Stream an adopted request's text deltas as they decode (the
        disaggregated analog of ``JaxLLMEngine.generate_stream``) — this
        replica's streams are never interrupted by prefill programs, the
        inter-token-latency property the pattern exists for.

        Each stream records its TTFT and inter-token-gap histograms
        (``deployment="llm_decode"``) — the exact per-request signals
        the continuous-batching serving gate measures against."""
        from ray_tpu.util import flight_recorder

        tele = flight_recorder.StreamTelemetry("llm_decode", "decode")
        outcome = "ok"
        try:
            for delta in self.engine.stream_request(request_id, timeout_s):
                tele.tick()
                yield delta
        except BaseException:
            outcome = "error"
            raise
        finally:
            tele.done(outcome)


class PrefillReplica:
    """Prefill-role replica (actor-friendly wrapper)."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None):
        self.engine = PrefillEngine(engine_cfg or EngineConfig())

    def prefill(
        self, prompt: str, params: Optional[SamplingParams] = None
    ) -> Dict[str, Any]:
        return self.engine.prefill(prompt, params)


class DisaggRouter:
    """Routes new requests to prefill replicas and continuations to decode
    replicas (the reference's prefill_decode serving-pattern router).

    Works with actor handles (``.remote()``/``ray_tpu.get``) or plain
    local instances (ducks on the presence of ``.prefill.remote``)."""

    def __init__(self, prefill_replicas: List[Any], decode_replicas: List[Any]):
        if not prefill_replicas or not decode_replicas:
            raise ValueError("need at least one prefill and one decode replica")
        self.prefill_replicas = list(prefill_replicas)
        self.decode_replicas = list(decode_replicas)
        self._p_rr = itertools.cycle(range(len(self.prefill_replicas)))
        self._d_rr = itertools.cycle(range(len(self.decode_replicas)))

    @staticmethod
    def _is_actor(h) -> bool:
        return hasattr(getattr(h, "prefill", None), "remote") or hasattr(
            getattr(h, "add_from_kv", None), "remote"
        )

    def generate(
        self,
        prompt: str,
        params: Optional[SamplingParams] = None,
        timeout_s: float = 300.0,
    ) -> dict:
        import ray_tpu
        from ray_tpu.util import flight_recorder, tracing

        p = self.prefill_replicas[next(self._p_rr)]
        d = self.decode_replicas[next(self._d_rr)]
        # One request-scoped span per generate: the prefill and decode
        # actor calls inside inherit the trace, so the router -> prefill
        # -> decode path exports as a single stitched cluster trace.
        # TTFT here is prompt-in to first-token-out (the prefill hop),
        # the disaggregation pattern's protected latency.
        t0 = time.perf_counter()
        ttft_s = None
        outcome = "ok"
        try:
            with tracing.start_span(
                "llm.disagg.generate", {"deployment": "llm_disagg"}
            ) as span:
                try:
                    if self._is_actor(p):
                        meta = ray_tpu.get(
                            p.prefill.remote(prompt, params),
                            timeout=timeout_s,
                        )
                        ttft_s = time.perf_counter() - t0
                        rid = ray_tpu.get(
                            d.add_from_kv.remote(meta), timeout=timeout_s
                        )
                        result = ray_tpu.get(d.run.remote(rid),
                                             timeout=timeout_s)
                    else:
                        meta = p.prefill(prompt, params)
                        ttft_s = time.perf_counter() - t0
                        rid = d.add_from_kv(meta)
                        result = d.run(rid, timeout_s=timeout_s)
                    span.set_attribute("ttft_s", ttft_s)
                except BaseException as e:
                    span.set_attribute("error", str(e))
                    raise
            return result
        except BaseException:
            outcome = "error"
            raise
        finally:
            flight_recorder.record_serve_request(
                "llm_disagg", "router", 0.0,
                ttft_s if ttft_s is not None
                else time.perf_counter() - t0,
                outcome=outcome,
            )

    def generate_many(
        self,
        prompts: List[str],
        params: Optional[SamplingParams] = None,
        timeout_s: float = 300.0,
    ) -> List[dict]:
        """Pipelined fan-out: all prefills dispatch first (spread over the
        prefill pool), continuations spread over the decode pool."""
        import ray_tpu

        if not self._is_actor(self.prefill_replicas[0]):
            return [self.generate(p, params, timeout_s) for p in prompts]
        # All prefills dispatch immediately (spread over the prefill
        # pool); each prompt's continuation pipeline (add_from_kv -> run)
        # starts the moment ITS prefill completes — no barrier, so one
        # slow prefill never delays the other prompts' decode starts.
        deadline = time.time() + timeout_s
        meta_refs = {
            self.prefill_replicas[next(self._p_rr)].prefill.remote(
                p, params
            ): i
            for i, p in enumerate(prompts)
        }
        run_refs: List[Any] = [None] * len(prompts)
        pending = list(meta_refs)
        while pending:
            ready, pending = ray_tpu.wait(
                pending, num_returns=1,
                timeout=max(0.0, deadline - time.time()),
            )
            if not ready:
                raise TimeoutError("prefill fan-out timed out")
            for ref in ready:
                i = meta_refs[ref]
                d = self.decode_replicas[next(self._d_rr)]
                meta = ray_tpu.get(ref, timeout=timeout_s)
                rid = ray_tpu.get(d.add_from_kv.remote(meta), timeout=timeout_s)
                run_refs[i] = d.run.remote(rid)
        return ray_tpu.get(run_refs, timeout=timeout_s)
