"""Prefill/decode disaggregated serving.

Reference: ray ``llm/_internal/serve/serving_patterns/prefill_decode/`` +
``engines/vllm/kv_transfer/`` — prefill replicas compute the prompt's KV
cache, decode replicas continue token generation, and the KV pages move
replica-to-replica without re-running the prompt.

TPU-native shape: the KV transfer rides the device-object plane
(``ray_tpu.collective.device_objects``) — the prefill replica keeps the
[L, 1, H, S, D] KV blocks resident and returns ``DeviceRef`` metadata;
the decode replica fetches point-to-point from the owner (ICI/DCN-safe:
same-process hits HBM directly, cross-process streams over the owner's
RPC channel) and splices the pages into its batch cache with one jitted
``dynamic_update_slice``.  The cross-process hop is zero-copy end to
end: ``device_fetch`` replies frame the KV block's host view as an
out-of-band buffer segment (no ``tobytes()`` flat copy — see
``core_worker.handle_device_fetch`` / docs/performance.md) and the
decode side rebuilds with ``np.frombuffer`` straight from the receive
buffer, so a KV handoff costs exactly one D2H and one H2D.  Compute stays in exactly two XLA programs per
replica role: prefill compiles only the prefill graph, decode only the
decode-step graph — each role's chip runs one static-shape program at
100% duty instead of interleaving both.

Why disaggregate (same motivation as the reference): prefill is
compute-bound and bursty, decode is HBM-bound and steady; separating them
lets each pool scale independently and keeps long prompts from stalling
token streams of in-flight requests.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..models import model_family
from ..models.gpt2_decode import sample_logits
from .engine import EngineConfig, JaxLLMEngine, SamplingParams
from .tokenizer import ByteTokenizer


class PrefillEngine:
    """Prefill-only engine: prompt -> (first token, resident KV pages).

    No batch slots, no decode program — one jitted prefill over a
    single-row cache; the row is published to the device-object store and
    ownership transfers to the fetching decode replica.
    """

    def __init__(self, cfg: EngineConfig, tokenizer=None):
        import jax

        self.cfg = cfg
        self.tokenizer = tokenizer or ByteTokenizer()
        mcfg = cfg.model
        fam = model_family(mcfg)
        self.family = fam
        if cfg.param_loader is not None:
            self.params = cfg.param_loader()
        else:
            self.params = fam.init(jax.random.PRNGKey(cfg.seed), mcfg)
        self._key = jax.random.PRNGKey(cfg.seed + 1)

        def prefill_row(params, tokens, length):
            import jax.numpy as jnp

            cache = fam.init_cache(mcfg, 1, cfg.max_seq_len)
            logits, cache = fam.prefill(
                params, tokens[None], jnp.asarray([length]), cache, mcfg
            )
            return logits[0], cache

        self._prefill_row = jax.jit(prefill_row)
        self._sample = jax.jit(
            sample_logits, static_argnames=("temperature", "top_k", "top_p")
        )

    def prefill(
        self, prompt: str, params: Optional[SamplingParams] = None
    ) -> Dict[str, Any]:
        """Run the prompt; return picklable metadata + KV DeviceRefs.

        The caller (router) hands the dict to a decode replica, which
        fetches and frees the refs — the KV pages live on this replica
        only until that single consumer collects them.
        """
        import jax

        from ..collective.device_objects import device_object_store

        from .engine import encode_prompt

        params = params or SamplingParams()
        token_ids = encode_prompt(self.tokenizer, prompt, self.cfg.max_seq_len)
        tokens = np.zeros(self.cfg.max_seq_len, np.int32)
        tokens[: len(token_ids)] = token_ids
        import jax.numpy as jnp

        logits, cache = self._prefill_row(
            self.params, jnp.asarray(tokens), len(token_ids)
        )
        self._key, sub = jax.random.split(self._key)
        first = int(
            np.asarray(
                self._sample(
                    logits[None], sub,
                    temperature=params.temperature,
                    top_k=params.top_k,
                    top_p=params.top_p,
                )
            )[0]
        )
        store = device_object_store()
        return {
            "prompt_len": len(token_ids),
            "first_token": first,
            "sampling": params,
            # The prompt's token ids + last-position logits ride along so
            # the decode side can index its prefix KV cache (block-chain
            # hashes) and re-sample the first token exactly on a cache
            # hit (llm.continuous_batching.PrefixKVCache).
            "token_ids": list(token_ids),
            "logits": np.asarray(logits, np.float32),
            "k_ref": store.put(cache["k"]),
            "v_ref": store.put(cache["v"]),
        }


def _missing_method(e: BaseException, name: str) -> bool:
    """True iff a remote error is the executor's missing-method
    AttributeError for ``name`` — matched on its exact signature, NOT a
    bare substring (a real failure RAISED INSIDE the method would also
    carry the method name in its task-error message, and swallowing that
    would silently demote a batched replica to the plain path)."""
    return f"has no attribute '{name}'" in str(e)


def fetch_prefill_kv(meta: Dict[str, Any]):
    """Collect (and free) the KV pages a ``PrefillEngine`` published for
    one prompt — THE consumer side of the zero-copy handoff, shared by
    every decode role and the bench harness so the protocol has exactly
    one implementation."""
    from ..collective.device_objects import device_object_store

    store = device_object_store()
    k = store.fetch(meta["k_ref"])
    v = store.fetch(meta["v_ref"])
    store.free(meta["k_ref"])
    store.free(meta["v_ref"])
    return k, v


class DecodeReplica:
    """Decode-role replica: adopts prefilled KV, streams decode steps.

    Wraps the standard engine (whose ``add_request_from_kv`` owns the
    disaggregated admission path); the prefill program is simply never
    compiled or run on this replica — all admissions arrive as KV pages."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None):
        self.engine = JaxLLMEngine(engine_cfg or EngineConfig())

    def add_from_kv(self, meta: Dict[str, Any]) -> int:
        """Fetch the KV pages from the prefill owner and enqueue."""
        k, v = fetch_prefill_kv(meta)
        return self.engine.add_request_from_kv(meta, k, v)

    def run(self, request_id: int, timeout_s: float = 300.0) -> dict:
        """Decode until this request finishes; returns its result.

        Deploy decode replicas with ``max_concurrency`` > 1: run() loops
        step the shared engine, and concurrent add_from_kv admissions
        (arriving on other lanes) join the SAME decode batch — on an
        exclusive actor each request would decode solo, which is the
        anti-pattern disaggregation exists to avoid."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self.engine._step_lock:
                done = self.engine._finished.pop(request_id, None)
                if done is None:
                    self.engine.step()
                    done = self.engine._finished.pop(request_id, None)
            if done is not None:
                return done
            if time.monotonic() > deadline:
                self.engine.cancel_request(request_id)
                raise TimeoutError(f"decode of request {request_id} timed out")

    def run_stream(self, request_id: int, timeout_s: float = 300.0):
        """Stream an adopted request's text deltas as they decode (the
        disaggregated analog of ``JaxLLMEngine.generate_stream``) — this
        replica's streams are never interrupted by prefill programs, the
        inter-token-latency property the pattern exists for.

        Each stream records its TTFT and inter-token-gap histograms
        (``deployment="llm_decode"``) — the exact per-request signals
        the continuous-batching serving gate measures against."""
        from ray_tpu.util import flight_recorder

        tele = flight_recorder.StreamTelemetry("llm_decode", "decode")
        outcome = "ok"
        try:
            for delta in self.engine.stream_request(request_id, timeout_s):
                tele.tick()
                yield delta
        except BaseException:
            outcome = "error"
            raise
        finally:
            tele.done(outcome)


class PrefillReplica:
    """Prefill-role replica (actor-friendly wrapper)."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None):
        self.engine = PrefillEngine(engine_cfg or EngineConfig())

    def prefill(
        self, prompt: str, params: Optional[SamplingParams] = None
    ) -> Dict[str, Any]:
        return self.engine.prefill(prompt, params)


class DisaggRouter:
    """Routes new requests to prefill replicas and continuations to decode
    replicas (the reference's prefill_decode serving-pattern router).

    Works with actor handles (``.remote()``/``ray_tpu.get``) or plain
    local instances (ducks on the presence of ``.prefill.remote``).

    **Prefix-cache-aware decode routing** (on by default when the decode
    pool supports it): the router hashes the prompt into block-chain keys
    (``llm.continuous_batching.prefix_block_keys``) and routes a request
    sharing a prefix with earlier traffic to the decode replica those
    requests landed on — the replica already holding the prefix KV
    blocks.  On a full-coverage hit the decode replica admits straight
    from its prefix cache (``try_add_cached``) and the prefill hop is
    skipped entirely; router affinity decisions and engine reuse are
    accounted separately (``site="router"`` vs ``site="engine"`` on the
    ``ray_tpu_llm_prefix_cache_*`` counters)."""

    def __init__(self, prefill_replicas: List[Any], decode_replicas: List[Any],
                 prefix_routing: Optional[bool] = None,
                 prefix_block_tokens: int = 16,
                 max_affinity_entries: int = 4096,
                 imbalance_factor: float = 2.0):
        if not prefill_replicas or not decode_replicas:
            raise ValueError("need at least one prefill and one decode replica")
        self.prefill_replicas = list(prefill_replicas)
        self.decode_replicas = list(decode_replicas)
        self._p_rr = itertools.cycle(range(len(self.prefill_replicas)))
        self._d_rr = itertools.cycle(range(len(self.decode_replicas)))
        if prefix_routing is None:
            # Actor handles synthesize ANY method name, so capability is
            # probed lazily per replica on first use (_try_cached);
            # affinity routing itself is safe for plain DecodeReplicas.
            prefix_routing = True
        self.prefix_routing = prefix_routing
        # replica id() -> supports try_add_cached (None = not yet probed).
        self._cached_support: Dict[int, Optional[bool]] = {}
        self.prefix_block_tokens = prefix_block_tokens
        self.max_affinity_entries = max_affinity_entries
        import threading

        self._tokenizer = ByteTokenizer()
        # block-chain key -> decode replica index (insertion-ordered LRU).
        # Routers live inside serve replicas where concurrent executor
        # threads route at once: the map (and its eviction iterator) is
        # lock-guarded — lookups/inserts only, never a blocking call.
        self._affinity: Dict[bytes, int] = {}
        self._affinity_lock = threading.Lock()
        # Load guard (same semantics as serve.PrefixAwareRouter): a warm
        # replica whose queue is imbalance_factor deeper than the
        # lightest replica's loses the request — a shared leading block
        # must not collapse the whole pool onto one replica.  Queue
        # loads are TTL-cached so the guard costs O(n) RPCs per interval,
        # not per request.
        self.imbalance_factor = imbalance_factor
        self._loads_ttl_s = 0.1
        self._loads_cache: tuple = (0.0, None)  # (ts, loads | None)
        self.router_hits = 0
        self.router_misses = 0

    @staticmethod
    def _is_actor(h) -> bool:
        return hasattr(getattr(h, "prefill", None), "remote") or hasattr(
            getattr(h, "add_from_kv", None), "remote"
        )

    # ------------------------------------------------- prefix-aware routing
    def _select_decode(self, prompt: str):
        """Pick the decode replica for ``prompt``: deepest block-chain
        affinity match wins (the replica already holding those KV
        blocks), round-robin otherwise.  Returns (replica, affinity_hit)
        and re-homes the prompt's chain onto the choice."""
        if not self.prefix_routing:
            return self.decode_replicas[next(self._d_rr)], False
        from .continuous_batching import full_prompt_key, prefix_block_keys

        token_ids = self._tokenizer.encode(prompt)
        # Block chain + the exact-prompt key: short prompts (< one block)
        # produce no chain keys at all, and exact repeats are the single
        # most common serving pattern — the full key gives both affinity.
        keys = prefix_block_keys(token_ids, self.prefix_block_tokens)
        keys.append(full_prompt_key(token_ids, self.prefix_block_tokens))
        with self._affinity_lock:
            idx = None
            exact = False
            for j in range(len(keys) - 1, -1, -1):  # deepest first
                idx = self._affinity.get(keys[j])
                if idx is not None and idx < len(self.decode_replicas):
                    exact = j == len(keys) - 1  # the exact-prompt key
                    break
                idx = None
        if idx is not None and not exact and len(self.decode_replicas) > 1:
            # Imbalance guard (queue probes happen OUTSIDE the affinity
            # lock): a block-level match is locality ADVICE — distinct
            # prompts sharing one leading block must not collapse the
            # pool onto one replica, so an overloaded advisory target
            # loses the request.  An EXACT-prompt match is exempt: that
            # replica holds this prompt's full KV, and re-homing it
            # trades a cache hit for a prefill.
            loads = self._decode_loads()
            if loads is not None:
                warm, lightest = loads[idx], min(loads)
                if warm > self.imbalance_factor * max(lightest, 1):
                    idx = None
        hit = idx is not None
        with self._affinity_lock:
            if idx is None:
                idx = next(self._d_rr)
            if hit:
                self.router_hits += 1
            else:
                self.router_misses += 1
            for key in keys:
                self._affinity[key] = idx
            while len(self._affinity) > self.max_affinity_entries:
                self._affinity.pop(next(iter(self._affinity)))
        from ray_tpu.util import flight_recorder

        flight_recorder.record_llm_prefix_lookup("router", hit)
        return self.decode_replicas[idx], hit

    def _decode_loads(self) -> Optional[List[int]]:
        """Per-decode-replica load (queued + decoding sequences) from the
        batched replicas' stats(), TTL-cached; None when unavailable
        (plain replicas / probe failure) — the guard then stands down."""
        import ray_tpu

        ts, loads = self._loads_cache
        now = time.monotonic()
        if ts > 0 and now - ts < self._loads_ttl_s:
            return loads  # a cached None (plain pool) also holds for TTL
        try:
            if self._is_actor(self.decode_replicas[0]):
                stats = ray_tpu.get(
                    [d.stats.remote() for d in self.decode_replicas],
                    timeout=5,
                )
            else:
                stats = [d.stats() for d in self.decode_replicas]
            loads = [
                int(s["occupancy"]) + int(s["queue_depth"]) for s in stats
            ]
        except Exception:  # noqa: BLE001 — guard degrades to affinity-only
            loads = None
        self._loads_cache = (now, loads)
        return loads

    def _try_cached(self, d, prompt: str, params, timeout_s: float):
        """Prefix-cache fast path if the replica supports it.  Actor
        handles synthesize any method name, so support is learned from
        the first call: a missing-method error marks the replica plain
        (DecodeReplica) and is never retried."""
        import ray_tpu

        key = id(d)
        if self._cached_support.get(key) is False:
            return None
        if not self._is_actor(d):
            if not hasattr(d, "try_add_cached"):
                self._cached_support[key] = False
                return None
            self._cached_support[key] = True
            return d.try_add_cached(prompt, params)
        try:
            rid = ray_tpu.get(
                d.try_add_cached.remote(prompt, params), timeout=timeout_s
            )
        except Exception as e:  # noqa: BLE001 — capability probe
            # Concurrent first calls may all be probing: re-raise only
            # when support was already CONFIRMED (a real failure on a
            # batched replica), not when a sibling thread just marked
            # the replica plain.
            if self._cached_support.get(key) is not True and (
                _missing_method(e, "try_add_cached")
            ):
                self._cached_support[key] = False
                return None
            raise
        self._cached_support[key] = True
        return rid

    def _admit(self, prompt: str, params, d, timeout_s: float):
        """Admit ``prompt`` on decode replica ``d``: prefix-cache fast
        path first (no prefill hop), else prefill + zero-copy KV handoff.
        Returns the replica-local request id."""
        import ray_tpu

        rid = self._try_cached(d, prompt, params, timeout_s)
        if rid is not None:
            return rid
        p = self.prefill_replicas[next(self._p_rr)]
        if self._is_actor(d):
            meta = ray_tpu.get(
                p.prefill.remote(prompt, params), timeout=timeout_s
            )
            return ray_tpu.get(d.add_from_kv.remote(meta), timeout=timeout_s)
        return d.add_from_kv(p.prefill(prompt, params))

    def _generate_on(self, d, prompt: str, params, timeout_s: float) -> dict:
        """Full generate on decode replica ``d``.  Batched actor replicas
        take the FUSED round trips (generate_cached: cached admission +
        completion in one call; run_from_kv: KV admission + completion in
        one call) so the hot repeat-prompt path costs one RPC like a
        monolithic engine call; plain replicas keep the two-phase path."""
        import ray_tpu

        if not self._is_actor(d):
            rid = self._admit(prompt, params, d, timeout_s)
            return d.run(rid, timeout_s=timeout_s)
        key = id(d)
        support = self._cached_support.get(key)
        result = None
        if support is not False:
            try:
                result = ray_tpu.get(
                    d.generate_cached.remote(prompt, params, timeout_s),
                    timeout=timeout_s,
                )
                self._cached_support[key] = True
            except Exception as e:  # noqa: BLE001 — capability probe
                if support is not True and _missing_method(
                    e, "generate_cached"
                ):
                    self._cached_support[key] = False
                else:
                    raise
        if result is not None:
            return result
        p = self.prefill_replicas[next(self._p_rr)]
        meta = ray_tpu.get(p.prefill.remote(prompt, params), timeout=timeout_s)
        if self._cached_support.get(key):
            return ray_tpu.get(
                d.run_from_kv.remote(meta, timeout_s), timeout=timeout_s
            )
        rid = ray_tpu.get(d.add_from_kv.remote(meta), timeout=timeout_s)
        return ray_tpu.get(d.run.remote(rid), timeout=timeout_s)

    def generate(
        self,
        prompt: str,
        params: Optional[SamplingParams] = None,
        timeout_s: float = 300.0,
    ) -> dict:
        import ray_tpu
        from ray_tpu.util import flight_recorder, tracing

        d, _ = self._select_decode(prompt)
        # One request-scoped span per generate: the prefill and decode
        # actor calls inside inherit the trace, so the router -> prefill
        # -> decode path exports as a single stitched cluster trace.
        # TTFT here is prompt-in to first-token-out (the admission hop —
        # prefill, or the prefix-cache fast path), the disaggregation
        # pattern's protected latency.
        t0 = time.perf_counter()
        ttft_s = None
        outcome = "ok"
        try:
            with tracing.start_span(
                "llm.disagg.generate", {"deployment": "llm_disagg"}
            ) as span:
                try:
                    result = self._generate_on(d, prompt, params, timeout_s)
                    # Fused round trips fold admission into completion,
                    # so router-side TTFT is whole-request latency; the
                    # decode engine records the true per-request TTFT
                    # under its own deployment tag.
                    ttft_s = time.perf_counter() - t0
                    span.set_attribute("ttft_s", ttft_s)
                except BaseException as e:
                    span.set_attribute("error", str(e))
                    raise
            return result
        except BaseException:
            outcome = "error"
            raise
        finally:
            flight_recorder.record_serve_request(
                "llm_disagg", "router", 0.0,
                ttft_s if ttft_s is not None
                else time.perf_counter() - t0,
                outcome=outcome,
            )

    def stream(self, prompt: str,
               params: Optional[SamplingParams] = None,
               timeout_s: float = 300.0):
        """Streaming generate through the disaggregated path: admit (prefix
        cache or prefill+KV handoff), then yield the decode replica's text
        deltas.  Inside a traced caller (e.g. the serve SSE path) the
        admission and decode calls inherit the active span, so one
        stitched trace covers router -> prefill -> decode."""
        import ray_tpu

        d, _ = self._select_decode(prompt)
        rid = self._admit(prompt, params, d, timeout_s)
        if self._is_actor(d):
            gen = d.run_stream.options(num_returns="streaming").remote(rid)
            for ref in gen:
                yield ray_tpu.get(ref, timeout=timeout_s)
        else:
            yield from d.run_stream(rid, timeout_s=timeout_s)

    def generate_many(
        self,
        prompts: List[str],
        params: Optional[SamplingParams] = None,
        timeout_s: float = 300.0,
    ) -> List[dict]:
        """Pipelined fan-out: all prefills dispatch first (spread over the
        prefill pool), continuations spread over the decode pool."""
        import ray_tpu

        if not self._is_actor(self.prefill_replicas[0]):
            return [self.generate(p, params, timeout_s) for p in prompts]
        # Each prompt routes to its prefix-affine decode replica first; a
        # prefix-cache hit admits immediately (no prefill dispatched).
        # The misses' prefills all dispatch up-front (spread over the
        # prefill pool); each prompt's continuation pipeline
        # (add_from_kv -> run) starts the moment ITS prefill completes —
        # no barrier, so one slow prefill never delays the other prompts'
        # decode starts.
        deadline = time.time() + timeout_s
        run_refs: List[Any] = [None] * len(prompts)
        meta_refs: Dict[Any, tuple] = {}
        # Cached-admission probes dispatch as refs FIRST and resolve
        # overlapped — a blocking probe per prompt would serialize N
        # round trips ahead of the prefill fan-out and break its
        # all-dispatch-immediately property.
        probes: List[tuple] = []
        for i, prompt in enumerate(prompts):
            d, _ = self._select_decode(prompt)
            key = id(d)
            if self._cached_support.get(key) is False or not hasattr(
                type(d) if not self._is_actor(d) else d, "try_add_cached"
            ):
                probes.append((i, prompt, d, None))
            elif self._is_actor(d):
                probes.append(
                    (i, prompt, d, d.try_add_cached.remote(prompt, params))
                )
            else:
                probes.append(
                    (i, prompt, d, d.try_add_cached(prompt, params))
                )
        for i, prompt, d, probe in probes:
            rid = None
            if probe is not None:
                if self._is_actor(d):
                    try:
                        rid = ray_tpu.get(probe, timeout=timeout_s)
                        self._cached_support[id(d)] = True
                    except Exception as e:  # noqa: BLE001 — probe
                        if self._cached_support.get(id(d)) is not True and (
                            _missing_method(e, "try_add_cached")
                        ):
                            self._cached_support[id(d)] = False
                        else:
                            raise
                else:
                    rid = probe
            if rid is not None:
                run_refs[i] = d.run.remote(rid)
            else:
                ref = self.prefill_replicas[next(self._p_rr)].prefill.remote(
                    prompt, params
                )
                meta_refs[ref] = (i, d)
        pending = list(meta_refs)
        while pending:
            ready, pending = ray_tpu.wait(
                pending, num_returns=1,
                timeout=max(0.0, deadline - time.time()),
            )
            if not ready:
                raise TimeoutError("prefill fan-out timed out")
            for ref in ready:
                i, d = meta_refs[ref]
                meta = ray_tpu.get(ref, timeout=timeout_s)
                rid = ray_tpu.get(d.add_from_kv.remote(meta), timeout=timeout_s)
                run_refs[i] = d.run.remote(rid)
        return ray_tpu.get(run_refs, timeout=timeout_s)
