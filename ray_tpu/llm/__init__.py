"""``ray_tpu.llm`` — LLM serving and batch inference.

Reference: ray ``python/ray/llm/`` — there a vLLM engine wrapper + OpenAI
server + batch processors; here the engine itself is TPU-native JAX
(KV-cache continuous batching over the GPT-2 family), the server is a
Serve app, and batch inference rides the Data layer's actor pools.
"""

from .engine import EngineConfig, JaxLLMEngine, SamplingParams  # noqa: F401
from .serve_app import build_disagg_openai_app, build_openai_app  # noqa: F401
from .batch import build_llm_processor  # noqa: F401
from .tokenizer import ByteTokenizer  # noqa: F401
from .disagg import (  # noqa: F401
    DecodeReplica,
    DisaggRouter,
    PrefillEngine,
    PrefillReplica,
)
from .continuous_batching import (  # noqa: F401
    BatchedDecodeReplica,
    ContinuousBatchingConfig,
    ContinuousBatchingEngine,
)
