"""``bench.py llm_load`` backend — continuous-batching LLM serving stages.

Run as a subprocess (``python -m ray_tpu.llm.bench_llm [--quick]``); each
stage prints one ``{"llm": {...}}`` JSON line that ``bench.py`` re-emits
into the summary.

Stages:

- ``llm_disagg_vs_mono_speedup`` (+ ``llm_batched_vs_plain_disagg_
  speedup``) — three serving patterns under the same concurrent batched
  load: monolithic single-engine actor, plain prefill/decode
  disaggregation (caller-stepped decode), and continuous-batching
  decode replicas with prefix routing.  All arms ALTERNATE back-to-back
  inside ONE window (this box swings ~2x window-to-window; the PR-8/9
  interleaving pattern makes the ratios trustworthy even when the
  absolute rates are not).  Best-of-N per arm with per-arm spread
  recorded.
- ``llm_load`` — the high-QPS load harness: thousands of concurrent
  streaming clients against one continuous-batching decode engine
  (the admission queue IS the concurrency; per-request TTFT /
  inter-token / queue-wait land in the PR-10 serving histograms
  engine-side, so no per-client consumer threads are needed).  Asserts
  IN-BENCH: p99 inter-token stall under a bound, and decode batch
  occupancy > 1.
- ``llm_disagg_stream_stall_speedup`` — the interference regime carried
  over from the retired core-suite stage: worst inter-token gap of a
  live stream while a long-prompt burst prefills, mono vs batched
  decode, arms alternating.

``--quick`` shrinks both to a smoke — the path tier-1 pins via
tests/test_continuous_batching.py.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List


def _emit(row: Dict[str, Any]) -> Dict[str, Any]:
    print(json.dumps({"llm": row}), flush=True)
    return row


def _tiny_engine_cfg(max_batch: int = 8, seed: int = 3):
    from ray_tpu.models.gpt2 import GPT2Config

    from .engine import EngineConfig

    return EngineConfig(
        model=GPT2Config.tiny(vocab_size=384, max_seq=64, dtype="float32"),
        max_batch_size=max_batch, max_seq_len=64, seed=seed,
    )


# ------------------------------------------------------- disagg A/B stage
def _drive_concurrent(fn, prompts: List[str], clients: int,
                      timeout_s: float) -> float:
    """Wall time for ``clients`` threads to push ``prompts`` through
    ``fn(prompt)`` (each client takes its share round-robin)."""
    errors: List[BaseException] = []

    def worker(idx: int):
        try:
            for p in prompts[idx::clients]:
                fn(p)
        except BaseException as e:  # noqa: BLE001 — surface after join
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(i,), name=f"llm-client-{i}",
                         daemon=True)
        for i in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s)
    if errors:
        raise errors[0]
    if any(t.is_alive() for t in threads):
        raise TimeoutError("load clients did not finish in time")
    return time.perf_counter() - t0


def bench_disagg_ab(quick: bool = False) -> List[Dict[str, Any]]:
    """Three serving patterns under the same concurrent load, ALL
    alternating back-to-back in one window (requires a running cluster):
    mono (one engine actor), disagg-plain (prefill/decode pools, callers
    step the decode engine), disagg-batched (continuous-batching decode
    replicas + prefix routing)."""
    import ray_tpu

    from .continuous_batching import BatchedDecodeReplica
    from .disagg import DecodeReplica, DisaggRouter, PrefillReplica
    from .engine import JaxLLMEngine, SamplingParams

    cfg = _tiny_engine_cfg()
    sampling = SamplingParams(max_tokens=6 if quick else 16, temperature=0.0)
    n_templates = 4 if quick else 8
    repeats = 2 if quick else 6
    clients = 4 if quick else 8
    trials = 1 if quick else 5
    # Serving-shaped request stream: a fixed template set, each repeated
    # (the regime prefix caching exists for).  The SAME stream drives
    # both arms; only the disagg arm can exploit the repeats.
    templates = [
        f"request template {i} " + "x" * (3 + i % 7)
        for i in range(n_templates)
    ]
    prompts = [templates[(j * 5 + i) % n_templates]
               for j in range(repeats) for i in range(n_templates)]

    actors = []
    try:
        Mono = ray_tpu.remote(num_cpus=0, max_concurrency=32)(JaxLLMEngine)
        mono = Mono.remote(cfg)
        actors.append(mono)

        Pre = ray_tpu.remote(num_cpus=0)(PrefillReplica)
        Dec = ray_tpu.remote(num_cpus=0, max_concurrency=64)(
            BatchedDecodeReplica
        )
        PlainDec = ray_tpu.remote(num_cpus=0, max_concurrency=16)(
            DecodeReplica
        )
        pre = [Pre.remote(cfg) for _ in range(2)]
        dec = [Dec.remote(cfg) for _ in range(2)]
        plain = [PlainDec.remote(cfg) for _ in range(2)]
        actors.extend(pre + dec + plain)
        router = DisaggRouter(pre, dec)
        plain_router = DisaggRouter(pre, plain, prefix_routing=False)

        def mono_gen(p):
            ray_tpu.get(mono.generate.remote([p], sampling), timeout=300)

        def disagg_gen(p):
            router.generate(p, sampling, timeout_s=300)

        def plain_gen(p):
            plain_router.generate(p, sampling, timeout_s=300)

        # Warmup: pre-compile every decode bucket on every replica, then
        # one full untimed pass per arm (mono engine compile + prefill
        # compile + disagg steady state: prefix cache hot).  Without
        # this, jit compiles land inside the first measured window and
        # masquerade as serving cost.
        ray_tpu.get([d.warm.remote() for d in dec], timeout=600)
        _drive_concurrent(mono_gen, prompts, clients, 600)
        _drive_concurrent(plain_gen, prompts, clients, 600)
        _drive_concurrent(disagg_gen, prompts, clients, 600)

        # ONE window, the three arms alternating back-to-back.  The gate
        # ratios are PAIRED per trial (each trial's arms run adjacent in
        # time, so box drift hits both sides) and reported as the median
        # pair ratio — a single lucky window for one arm cannot flip the
        # gate the way best-of-per-arm can on a box with ~2x swings.
        import statistics

        mono_walls, plain_walls, disagg_walls = [], [], []
        for _ in range(trials):
            mono_walls.append(
                _drive_concurrent(mono_gen, prompts, clients, 600)
            )
            plain_walls.append(
                _drive_concurrent(plain_gen, prompts, clients, 600)
            )
            disagg_walls.append(
                _drive_concurrent(disagg_gen, prompts, clients, 600)
            )
        mono_best = min(mono_walls)
        plain_best = min(plain_walls)
        disagg_best = min(disagg_walls)
        mono_ratios = sorted(
            m / d for m, d in zip(mono_walls, disagg_walls)
        )
        plain_ratios = sorted(
            p / d for p, d in zip(plain_walls, disagg_walls)
        )

        def spread(vals):
            return round((max(vals) - min(vals)) / max(vals), 3) if vals else 0

        dec_stats = [ray_tpu.get(d.stats.remote(), timeout=60) for d in dec]
        max_occ = max(s["max_occupancy"] for s in dec_stats)
        cache_hits = sum(
            s["prefix_cache"]["hits"] for s in dec_stats
        )
        n_prompts = len(prompts)
        rows = [
            _emit({
                "metric": "llm_mono_batched_load_s",
                "value": round(mono_best, 4),
                "spread": spread(mono_walls),
                "prompts": n_prompts, "templates": n_templates,
                "clients": clients, "trials": trials,
            }),
            _emit({
                "metric": "llm_disagg_plain_load_s",
                "value": round(plain_best, 4),
                "spread": spread(plain_walls),
                "prompts": n_prompts, "templates": n_templates,
                "clients": clients, "trials": trials,
            }),
            _emit({
                "metric": "llm_disagg_batched_load_s",
                "value": round(disagg_best, 4),
                "spread": spread(disagg_walls),
                "prompts": n_prompts, "templates": n_templates,
                "clients": clients, "trials": trials,
            }),
            _emit({
                "metric": "llm_disagg_vs_mono_speedup",
                "value": round(statistics.median(mono_ratios), 4),
                "interleaved": True,
                "paired": "median of per-trial mono/batched ratios",
                "trials": trials,
                "ratio_min": round(mono_ratios[0], 3),
                "ratio_max": round(mono_ratios[-1], 3),
                "spread_mono": spread(mono_walls),
                "spread_disagg": spread(disagg_walls),
                "decode_max_occupancy": max_occ,
                "prefix_cache_hits": cache_hits,
                "router_hits": router.router_hits,
            }),
            _emit({
                "metric": "llm_batched_vs_plain_disagg_speedup",
                "value": round(statistics.median(plain_ratios), 4),
                "interleaved": True,
                "paired": "median of per-trial plain/batched ratios",
                "ratio_min": round(plain_ratios[0], 3),
                "ratio_max": round(plain_ratios[-1], 3),
                "spread_plain": spread(plain_walls),
                "spread_batched": spread(disagg_walls),
            }),
        ]
        if not quick and max_occ <= 1:
            raise AssertionError(
                f"decode replicas never batched (max occupancy {max_occ})"
            )
        return rows
    finally:
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# ---------------------------------------------------- interference stage
def bench_interference(quick: bool = False) -> List[Dict[str, Any]]:
    """Stream-stall protection A/B (the property disaggregation exists
    for, carried over from the retired core-suite stage): a live token
    stream must not freeze while a burst of long prompts prefills.  Mono
    runs prefill programs inside its decode loop — every in-flight
    stream stalls for whole prefill durations; the batched decode
    replica never compiles or runs prefill, so the burst only ADDS
    sequences to its running batch.  Arms alternate back-to-back;
    worst inter-token gap per arm, best-of-trials."""
    import threading

    import ray_tpu
    from ray_tpu.models.gpt2 import GPT2Config

    from .continuous_batching import BatchedDecodeReplica
    from .disagg import DisaggRouter, PrefillReplica
    from .engine import EngineConfig, JaxLLMEngine, SamplingParams

    # vocab_size=258 == the byte tokenizer's full id space (256 bytes +
    # BOS/EOS): a random-init model's greedy argmax can otherwise fixate
    # on an undecodable id, and a stream of empty text deltas measures
    # nothing.
    if quick:
        model = GPT2Config.tiny(vocab_size=258, max_seq=64, dtype="float32")
        seq_len, stream_tokens, n_burst, trials = 64, 24, 3, 1
    else:
        model = GPT2Config(
            n_layer=4, n_head=8, d_model=256, vocab_size=258, max_seq=256
        )
        seq_len, stream_tokens, n_burst, trials = 256, 100, 8, 2
    cfg = EngineConfig(
        model=model, max_batch_size=4, max_seq_len=seq_len, seed=3
    )
    # stop_token=-1: the stream must live its full token budget to be a
    # stall instrument — a random-init model's greedy EOS (or a run of
    # undecodable byte-tokenizer ids) would end/empty the stream and
    # leave no gaps to measure.
    stream_s = SamplingParams(max_tokens=stream_tokens, temperature=0.0,
                              stop_token=-1)
    burst_s = SamplingParams(max_tokens=4, temperature=0.0, stop_token=-1)
    burst_prompts = [
        ("load-" + "y" * (seq_len - 40) + f"-{i}") for i in range(n_burst)
    ]

    def max_gap(ts):
        return max((b - a for a, b in zip(ts, ts[1:])), default=0.0)

    actors = []
    try:
        Mono = ray_tpu.remote(num_cpus=0, max_concurrency=16)(JaxLLMEngine)
        mono = Mono.remote(cfg)
        Pre = ray_tpu.remote(num_cpus=0)(PrefillReplica)
        Dec = ray_tpu.remote(num_cpus=0, max_concurrency=32)(
            BatchedDecodeReplica
        )
        pre = [Pre.remote(cfg) for _ in range(2)]
        dec = [Dec.remote(cfg)]
        actors.extend([mono] + pre + dec)
        router = DisaggRouter(pre, dec)
        ray_tpu.get(dec[0].warm.remote(), timeout=600)
        ray_tpu.get(mono.generate.remote(["warm"], burst_s), timeout=600)
        router.generate("warm", burst_s, timeout_s=600)

        def run_arm(stream_fn, burst_fn):
            ts: List[float] = []

            def stream():
                for _ in stream_fn():
                    ts.append(time.perf_counter())

            st = threading.Thread(target=stream, daemon=True,
                                  name="llm-itf-stream")
            st.start()
            time.sleep(0.3)
            burst = [
                threading.Thread(target=burst_fn, args=(p,), daemon=True,
                                 name="llm-itf-burst")
                for p in burst_prompts
            ]
            for t in burst:
                t.start()
            for t in burst:
                t.join(timeout=600)
            st.join(timeout=600)
            return max_gap(ts)

        def mono_stream():
            return mono.generate_stream.options(
                num_returns="streaming"
            ).remote("the stream", stream_s)

        def mono_burst(p):
            ray_tpu.get(mono.generate.remote([p], burst_s), timeout=600)

        def dis_stream():
            return router.stream("the stream", stream_s, timeout_s=600)

        def dis_burst(p):
            router.generate(p, burst_s, timeout_s=600)

        mono_stalls, dis_stalls = [], []
        for _ in range(trials):  # arms alternate back-to-back
            mono_stalls.append(run_arm(mono_stream, mono_burst))
            dis_stalls.append(run_arm(dis_stream, dis_burst))
        mono_stall = min(mono_stalls)
        dis_stall = min(dis_stalls)
        return [
            _emit({
                "metric": "llm_mono_stream_max_stall_s",
                "value": round(mono_stall, 4), "trials": trials,
            }),
            _emit({
                "metric": "llm_disagg_stream_max_stall_s",
                "value": round(dis_stall, 4), "trials": trials,
            }),
            _emit({
                "metric": "llm_disagg_stream_stall_speedup",
                "value": round(mono_stall / max(dis_stall, 1e-4), 4),
                "interleaved": True,
            }),
        ]
    finally:
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# ------------------------------------------------------------ load stage
def bench_load(quick: bool = False) -> List[Dict[str, Any]]:
    """Thousands of concurrent streaming clients against one continuous-
    batching engine (in-process: the admission queue carries the
    concurrency; serving telemetry is recorded engine-side per request).
    Asserts the p99 inter-token stall bound and occupancy > 1."""
    from ray_tpu.util import metrics as _metrics
    from ray_tpu.util import obs as _obs

    from .continuous_batching import (
        ContinuousBatchingConfig,
        ContinuousBatchingEngine,
    )
    from .disagg import PrefillEngine
    from .engine import SamplingParams

    n_clients = 32 if quick else 2000
    max_tokens = 4 if quick else 12
    stall_bound_s = 5.0 if quick else 1.0
    feeders = 2
    deployment = "llm_load"

    cfg = _tiny_engine_cfg()
    # Warmup requests record under a separate deployment tag so their
    # compile-time stalls can't pollute the asserted load histograms.
    cb = ContinuousBatchingConfig(
        starvation_timeout_s=5.0, deployment=deployment + "_warmup",
        prefix_cache_tokens=8192,
    )
    engine = ContinuousBatchingEngine(cfg, cb)
    engine.start()
    pre = PrefillEngine(cfg)
    sampling = SamplingParams(max_tokens=max_tokens, temperature=0.0)

    # 4 hot prompts (shared-prefix traffic) + cold uniques: ~70% of
    # clients hit the prefix cache full-coverage fast path, the rest pay
    # a prefill — the hot/cold mix the prefix router exists for.
    hot = [f"system prompt {i}: you are a helpful bench" for i in range(4)]
    # Seed the prefix cache AND pre-warm every bucket's compiled programs
    # (submitting max_batch_size requests back-to-back drives the bucket
    # to its max, so no decode/insert compile lands inside the measured
    # window — compile gaps would masquerade as inter-token stalls).
    warm = hot + [f"warm pad {i}" for i in range(cfg.max_batch_size - len(hot))]
    try:
        engine.compile_buckets()
        for p in warm:
            meta = pre.prefill(p, sampling)
            _load_admit_local(engine, meta)
        while engine.has_unfinished():
            time.sleep(0.02)
        engine.cb.deployment = deployment

        lock = threading.Lock()
        stats = {"hot": 0, "cold": 0, "submitted": 0}

        def feed(idx: int):
            for i in range(idx, n_clients, feeders):
                if i % 10 < 7:
                    p = hot[i % len(hot)]
                    rid = engine.submit_cached(p, sampling)
                    if rid is None:  # evicted: repave via prefill
                        _load_admit_local(engine, pre.prefill(p, sampling))
                        kind = "cold"
                    else:
                        kind = "hot"
                else:
                    p = f"cold client {i} " + "y" * (i % 11)
                    _load_admit_local(engine, pre.prefill(p, sampling))
                    kind = "cold"
                with lock:
                    stats[kind] += 1
                    stats["submitted"] += 1

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=feed, args=(i,), name=f"llm-feeder-{i}",
                             daemon=True)
            for i in range(feeders)
        ]
        for t in threads:
            t.start()
        # Deadlines must fire INSIDE bench.py's 600s subprocess cap:
        # an in-bench TimeoutError exits nonzero (rows printed so far
        # are salvaged), while a subprocess-level TimeoutExpired loses
        # every row of the stage.
        for t in threads:
            t.join(timeout=240)
        if any(t.is_alive() for t in threads):
            raise TimeoutError("llm_load feeders hung")
        deadline = time.monotonic() + 240
        while engine.has_unfinished():
            if time.monotonic() > deadline:
                raise TimeoutError("llm_load drain timed out")
            time.sleep(0.05)
        wall = time.perf_counter() - t0

        est = engine.stats()
        serving = _obs.serving_stats(
            snapshot=_metrics.payload_snapshot() or {}
        ).get(deployment, {})
        itl = serving.get("inter_token") or {}
        ttft = serving.get("ttft") or {}
        total_requests = n_clients + len(warm)
        mean_occ = (
            (total_requests * max_tokens - total_requests) / est["steps"]
            if est["steps"] else 0.0
        )
        rows = [
            _emit({
                "metric": "llm_load_requests_per_s",
                "value": round(n_clients / wall, 2),
                "clients": n_clients,
                "wall_s": round(wall, 2),
                "hot": stats["hot"], "cold": stats["cold"],
                "prefix_cache": est["prefix_cache"],
                "preemptions": est["preempted"],
            }),
            _emit({
                "metric": "llm_load_batch_occupancy_max",
                "value": est["max_occupancy"],
                "mean_occupancy": round(mean_occ, 2),
                "decode_steps": est["steps"],
                "bucket_final": est["bucket"],
            }),
            _emit({
                "metric": "llm_load_p99_inter_token_s",
                "value": round(itl.get("p99_s", 0.0), 4),
                "mean_s": round(itl.get("mean_s", 0.0), 5),
                "n": itl.get("count", 0),
                "bound_s": stall_bound_s,
            }),
            _emit({
                "metric": "llm_load_p99_ttft_s",
                "value": round(ttft.get("p99_s", 0.0), 4),
                "p50_s": round(ttft.get("p50_s", 0.0), 4),
                "mean_s": round(ttft.get("mean_s", 0.0), 4),
                "note": "closed-burst arrivals: TTFT includes queue wait "
                        "by design",
            }),
        ]
        # In-bench acceptance: the stall bound and real batching.
        if est["max_occupancy"] <= 1:
            raise AssertionError(
                f"decode never batched (max occupancy {est['max_occupancy']})"
            )
        if itl.get("count") and itl["p99_s"] > stall_bound_s:
            raise AssertionError(
                f"p99 inter-token stall {itl['p99_s']:.3f}s exceeds the "
                f"{stall_bound_s}s bound"
            )
        if not quick and stats["hot"] == 0:
            raise AssertionError("prefix-cache fast path never hit")
        return rows
    finally:
        engine.stop()


def _load_admit_local(engine, meta) -> int:
    """Local (same-process) KV handoff into the batching engine — same
    consumer protocol as the decode replicas (`disagg.fetch_prefill_kv`)
    so the bench measures the admission path serving uses."""
    from .disagg import fetch_prefill_kv

    k, v = fetch_prefill_kv(meta)
    return engine.submit_kv(meta, k, v)


def main(argv=None) -> int:
    import sys

    quick = "--quick" in (argv if argv is not None else sys.argv[1:])
    bench_load(quick)

    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        bench_disagg_ab(quick)
        bench_interference(quick)
    finally:
        ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
