"""Elastic capacity: the drain state machine and launch backoff that turn
the reconcile loop's decisions into safe node lifecycle transitions.

Scale-down is a protocol, not a call (ISSUE: the old loop did a direct
``provider.terminate_node`` under running workloads):

    idle decision -> drain_node (control plane marks the node
    unschedulable, evicts resident placement groups through the PR-15
    ``prepare_evict`` checkpoint protocol, migrates plain actors)
    -> poll drain_status until the node holds no placement groups, no
    actors, and no busy leases -> provider terminate -> drain_complete
    (the control plane retires the entry immediately instead of waiting
    out the health-check timeout).

Drain flags on the control plane are in-memory: after a failover the
poll sees ``draining=False`` on a live node and simply re-issues the
idempotent mark, so the machine survives leader changes without its own
persistence.

Scale-up failures gate through :class:`LaunchBackoff` — decorrelated
jitter (``core.rpc.next_backoff_delay``) per node type with a
consecutive-failure counter surfaced in the decision, so a broken
provider converges to a slow retry cadence instead of a hot loop
(reference: ray autoscaler v2's per-node-type launch failure tracking).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..core.config import GlobalConfig
from ..core.rpc import next_backoff_delay
from ..util import flight_recorder
from .provider import NodeProvider

logger = logging.getLogger(__name__)


# --------------------------------------------------------- launch backoff
@dataclass
class LaunchBackoff:
    """Per-node-type launch gate: closed for a jittered, growing window
    after each provider create failure; any success resets it."""

    base_s: float = 1.0
    cap_s: float = 30.0
    consecutive_failures: int = 0
    _gate_until: float = 0.0
    _prev_delay: float = 0.0

    def ready(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.monotonic()) >= self._gate_until

    def remaining_s(self, now: Optional[float] = None) -> float:
        now = now if now is not None else time.monotonic()
        return max(0.0, self._gate_until - now)

    def record_failure(self, now: Optional[float] = None) -> float:
        """Close the gate; returns the chosen delay."""
        now = now if now is not None else time.monotonic()
        self.consecutive_failures += 1
        self._prev_delay = next_backoff_delay(
            self._prev_delay or self.base_s, base=self.base_s, cap=self.cap_s
        )
        self._gate_until = now + self._prev_delay
        return self._prev_delay

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._gate_until = 0.0
        self._prev_delay = 0.0


# ------------------------------------------------------ drain state machine
@dataclass
class DrainingNode:
    provider_id: str
    node_id_hex: Optional[str]
    cause: str
    started: float  # monotonic
    marked: bool = False  # control plane acked the drain mark

    def public_info(self, now: Optional[float] = None) -> dict:
        now = now if now is not None else time.monotonic()
        return {
            "provider_id": self.provider_id,
            "node_id": self.node_id_hex,
            "cause": self.cause,
            "age_s": round(now - self.started, 3),
        }


class NodeDrainer:
    """Owns every in-flight drain; driven once per reconcile round from
    the autoscaler thread.

    ``call`` is a synchronous control-plane RPC, ``(method, payload) ->
    reply`` — the autoscaler's persistent retryable client, so a drain in
    flight survives control-plane failover."""

    def __init__(self, call: Callable[..., dict], provider: NodeProvider,
                 timeout_s: Optional[float] = None):
        self._call = call
        self._provider = provider
        self._timeout_s = timeout_s
        self._active: Dict[str, DrainingNode] = {}
        self.stats = {"drained": 0, "timeout": 0, "cancelled": 0}

    @property
    def timeout_s(self) -> float:
        if self._timeout_s is not None:
            return self._timeout_s
        return GlobalConfig.drain_timeout_s

    def is_draining(self, provider_id: str) -> bool:
        return provider_id in self._active

    def active(self) -> List[dict]:
        now = time.monotonic()
        return [d.public_info(now) for d in self._active.values()]

    def request(self, provider_id: str, node_id_hex: Optional[str],
                cause: str = "idle timeout") -> None:
        """Begin draining one node (idempotent per provider id)."""
        if provider_id in self._active:
            return
        entry = DrainingNode(
            provider_id=provider_id, node_id_hex=node_id_hex,
            cause=cause, started=time.monotonic(),
        )
        self._active[provider_id] = entry
        flight_recorder.record_autoscaler_drain("started")
        logger.info("draining %s (node %s): %s", provider_id,
                    node_id_hex, cause)
        self._mark(entry)

    def _mark(self, entry: DrainingNode) -> None:
        if entry.node_id_hex is None:
            # Never registered with the control plane (crashed during
            # provisioning): nothing to mark, the timeout path terminates.
            return
        try:
            reply = self._call(
                "drain_node",
                {"node_id": entry.node_id_hex, "cause": entry.cause},
            )
            entry.marked = bool(reply.get("ok"))
        except Exception as e:  # noqa: BLE001 — re-marked on next poll
            logger.warning("drain_node mark for %s failed: %s",
                           entry.provider_id, e)

    def cancel(self, provider_id: str) -> None:
        entry = self._active.pop(provider_id, None)
        if entry is None:
            return
        if entry.node_id_hex is not None:
            try:
                self._call(
                    "drain_node",
                    {"node_id": entry.node_id_hex, "cancel": True},
                )
            except Exception as e:  # noqa: BLE001 — node may be gone
                logger.warning("drain cancel for %s failed: %s",
                               provider_id, e)
        self.stats["cancelled"] += 1
        flight_recorder.record_autoscaler_drain("cancelled")

    def poll(self) -> List[str]:
        """Advance every in-flight drain one step; returns the provider
        ids terminated this round."""
        finished: List[str] = []
        now = time.monotonic()
        for pid, entry in list(self._active.items()):
            age = now - entry.started
            status: Optional[dict] = None
            if entry.node_id_hex is not None:
                try:
                    status = self._call(
                        "drain_status", {"node_id": entry.node_id_hex}
                    )
                except Exception as e:  # noqa: BLE001 — CP unreachable; retry next round
                    logger.warning("drain_status for %s failed: %s", pid, e)
            if status is not None:
                if (
                    status.get("known")
                    and status.get("alive")
                    and not status.get("draining")
                    and not status.get("drained")
                ):
                    # The control plane lost the flag (failover / restart):
                    # drain_node is idempotent, re-issue the mark.
                    self._mark(entry)
            drained = bool(status and status.get("drained"))
            if drained or age >= self.timeout_s:
                outcome = "drained" if drained else "timeout"
                self._terminate(entry, outcome)
                finished.append(pid)
        return finished

    def _terminate(self, entry: DrainingNode, outcome: str) -> None:
        try:
            self._provider.terminate_node(entry.provider_id)
            flight_recorder.record_autoscaler_termination(outcome)
            logger.info("terminated %s after drain (%s)",
                        entry.provider_id, outcome)
        except Exception as e:  # noqa: BLE001 — provider flake; record and move on
            logger.warning("terminate of %s failed: %s",
                           entry.provider_id, e)
            flight_recorder.record_autoscaler_termination("error")
        if entry.node_id_hex is not None:
            try:
                # Prompt retirement: without this the control plane waits
                # out the health-check timeout to declare the node dead.
                self._call("drain_complete", {"node_id": entry.node_id_hex})
            except Exception as e:  # noqa: BLE001 — health check retires it anyway
                logger.debug("drain_complete for %s failed: %s",
                             entry.provider_id, e)
        duration = time.monotonic() - entry.started
        flight_recorder.record_autoscaler_drain(outcome, duration)
        self.stats[outcome] = self.stats.get(outcome, 0) + 1
        self._active.pop(entry.provider_id, None)


# ------------------------------------------------------------ status panel
def build_status(decision, per_type: Dict[str, int],
                 backoffs: Dict[str, LaunchBackoff],
                 drainer: NodeDrainer, provider_nodes: int) -> dict:
    """The autoscaler panel blob published to control-plane KV (namespace
    ``autoscaler``) each round — ``cli status`` and ``/api/cluster``
    render it verbatim."""
    now = time.monotonic()
    return {
        "last_decision": {
            "to_launch": dict(decision.to_launch),
            "to_terminate": list(decision.to_terminate),
            "infeasible": len(decision.infeasible),
        },
        "pending_demand": {
            "count": decision.pending_demand,
            "resources": dict(decision.pending_resources),
        },
        "node_types": {
            tname: {
                "count": per_type.get(tname, 0),
                "launch_failures": b.consecutive_failures,
                "backoff_remaining_s": round(b.remaining_s(now), 3),
            }
            for tname, b in backoffs.items()
        },
        "draining": drainer.active(),
        "drain_stats": dict(drainer.stats),
        "provider_nodes": provider_nodes,
    }
