"""``ray_tpu.autoscaler`` — reconciler-style cluster autoscaling.

Role-equivalent of the reference's autoscaler v2 (ray
``python/ray/autoscaler/v2/autoscaler.py:50``): a reconciler polls the
control plane's load state (pending actors / placement groups / queued
leases / explicit requests), bin-packs unmet demand onto configured node
types, and drives a ``NodeProvider`` to launch/terminate nodes.  The TPU
twist: node types are *slices* — a ``TPU-v5e-8`` node type launches a whole
host with its chips, and gang demands (placement groups) are packed
slice-atomically.
"""

from .config import AutoscalingConfig, NodeTypeConfig  # noqa: F401
from .autoscaler import Autoscaler, wait_for_nodes  # noqa: F401
from .elastic import LaunchBackoff, NodeDrainer  # noqa: F401
from .command_runner import (  # noqa: F401
    CommandRunner,
    LocalCommandRunner,
    ManagedVMProvider,
    SSHCommandRunner,
)
from .provider import FakeMultiNodeProvider, NodeProvider  # noqa: F401
from .sdk import request_resources  # noqa: F401
