"""Node providers: how the autoscaler launches and terminates machines.

Reference: ray ``python/ray/autoscaler/node_provider.py`` (v1 ABC) and the
``FakeMultiNodeProvider`` testing trick
(``autoscaler/_private/fake_multi_node/node_provider.py:237``): fake nodes
are real node-agent processes on this machine, each believing it is a
distinct node — so autoscaler end-to-end tests run without a cloud.

Every launched node carries two labels the autoscaler uses to reconcile
provider state against the control plane's node table:
``rtpu-node-type`` and ``rtpu-provider-id``.
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional

from .config import NodeTypeConfig

NODE_TYPE_LABEL = "rtpu-node-type"
PROVIDER_ID_LABEL = "rtpu-provider-id"


class NodeProvider:
    """ABC.  Implementations must be idempotent and tolerate repeated
    terminate calls."""

    def create_node(self, node_type: NodeTypeConfig) -> str:
        """Launch one node of the given type; returns a provider id."""
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """provider_id -> node_type name."""
        raise NotImplementedError

    def shutdown(self) -> None:
        for pid in list(self.non_terminated_nodes()):
            self.terminate_node(pid)


class FakeMultiNodeProvider(NodeProvider):
    """Launches real local node-agent processes joined to an existing
    cluster (the reference's fake-multinode analog).

    Fault hooks (driven by ``devtools/chaos.py`` injectors, all through
    the real reconcile loop):

    - ``fault_create_errors``: the next N ``create_node`` calls raise —
      the backoff-convergence scenario.
    - ``fault_create_delay_s``: ``create_node`` returns a provider id
      immediately but the node's processes start only after the delay —
      slow provisioning, during which the decision must not
      double-launch.
    - ``kill_node``: kill a node's processes while KEEPING the provider
      record — a crashed VM the cloud API still reports as running; the
      autoscaler's reclaim pass must converge it.
    """

    def __init__(self, cp_address: str, session_id: str):
        self._cp_address = cp_address
        self._session_id = session_id
        self._nodes: Dict[str, tuple] = {}  # provider_id -> (type_name, Node)
        self._lock = threading.Lock()
        self.fault_create_errors = 0
        self.fault_create_delay_s = 0.0
        self.create_calls = 0
        self.terminate_calls = 0

    def create_node(self, node_type: NodeTypeConfig) -> str:
        from ..core.node import Node

        self.create_calls += 1
        with self._lock:
            if self.fault_create_errors > 0:
                self.fault_create_errors -= 1
                raise RuntimeError(
                    "chaos: provider refused create_node "
                    f"({self.fault_create_errors} more failures queued)"
                )
            delay = self.fault_create_delay_s
        provider_id = f"fake-{uuid.uuid4().hex[:8]}"
        labels = dict(node_type.labels)
        labels[NODE_TYPE_LABEL] = node_type.name
        labels[PROVIDER_ID_LABEL] = provider_id
        resources = dict(node_type.resources)
        node = Node(
            head=False,
            cp_address=self._cp_address,
            resources=resources,
            labels=labels,
            session_id=self._session_id,
            num_cpus=resources.get("CPU", 1),
        )
        self._nodes[provider_id] = (node_type.name, node)
        if delay > 0:
            # Slow provisioning: the id exists (non_terminated_nodes
            # reports it — a real cloud shows the VM as PROVISIONING)
            # but the agent joins late.
            timer = threading.Timer(delay, self._deferred_start,
                                    args=(provider_id, node))
            timer.daemon = True
            timer.name = f"rtpu-fake-provision-{provider_id}"
            timer.start()
        else:
            node.start()
        return provider_id

    def _deferred_start(self, provider_id: str, node) -> None:
        with self._lock:
            if provider_id not in self._nodes:
                return  # terminated while provisioning
        try:
            node.start()
        except Exception:  # noqa: BLE001 — raced terminate kills the start
            from ..util import flight_recorder

            flight_recorder.count_suppressed("fake_provider_deferred_start")

    def terminate_node(self, provider_id: str) -> None:
        self.terminate_calls += 1
        with self._lock:
            entry = self._nodes.pop(provider_id, None)
        if entry is not None:
            _, node = entry
            node.pg.kill_all()

    def kill_node(self, provider_id: str) -> None:
        """Chaos: crash the node's processes but keep the provider record
        (the cloud API has not noticed the VM die)."""
        entry = self._nodes.get(provider_id)
        if entry is not None:
            _, node = entry
            node.pg.kill_all()

    def non_terminated_nodes(self) -> Dict[str, str]:
        return {pid: tname for pid, (tname, _) in self._nodes.items()}


class GKETPUProvider(NodeProvider):
    """GKE/GCE TPU provider skeleton: shells out to ``gcloud`` to create and
    delete TPU VM slices (reference precedent: the GCP node provider,
    ``autoscaler/_private/gcp/``, and TPU pod metadata in
    ``_private/accelerators/tpu.py:267-672``).  Requires ``gcloud`` on PATH
    and is exercised only against a real project — tests use
    ``FakeMultiNodeProvider``."""

    def __init__(
        self,
        project: str,
        zone: str,
        cluster_name: str,
        cp_address: str,
        accelerator_type: str = "v5litepod-8",
        runtime_version: str = "tpu-ubuntu2204-base",
    ):
        import shutil

        if shutil.which("gcloud") is None:
            raise RuntimeError("GKETPUProvider requires the gcloud CLI")
        self._project = project
        self._zone = zone
        self._cluster = cluster_name
        self._cp_address = cp_address
        self._accel = accelerator_type
        self._runtime = runtime_version
        self._nodes: Dict[str, str] = {}

    def _run(self, *args: str) -> str:
        import subprocess

        return subprocess.check_output(
            ["gcloud", *args, f"--project={self._project}",
             f"--zone={self._zone}", "--format=json"],
            text=True,
        )

    def create_node(self, node_type: NodeTypeConfig) -> str:
        provider_id = f"{self._cluster}-{node_type.name}-{uuid.uuid4().hex[:6]}"
        accel = str(node_type.node_config.get("accelerator_type", self._accel))
        startup = (
            f"python -m ray_tpu start --address={self._cp_address} "
            f"--labels '{{\"{NODE_TYPE_LABEL}\": \"{node_type.name}\", "
            f"\"{PROVIDER_ID_LABEL}\": \"{provider_id}\"}}'"
        )
        self._run(
            "compute", "tpus", "tpu-vm", "create", provider_id,
            f"--accelerator-type={accel}",
            f"--version={self._runtime}",
            f"--metadata=startup-script={startup}",
        )
        self._nodes[provider_id] = node_type.name
        return provider_id

    def terminate_node(self, provider_id: str) -> None:
        if provider_id in self._nodes:
            self._run("compute", "tpus", "tpu-vm", "delete", provider_id,
                      "--quiet")
            self._nodes.pop(provider_id, None)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._nodes)
