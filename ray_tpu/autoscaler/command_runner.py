"""Command runners: how the autoscaler reaches machines it launched.

Reference: ray ``python/ray/autoscaler/_private/command_runner.py`` —
``SSHCommandRunner`` (and its docker wrapper) runs file syncs + setup
commands + ``ray start`` on freshly provisioned nodes.  Same split here:

* ``CommandRunner`` — the interface (``run``, ``sync_up``).
* ``SSHCommandRunner`` — subprocess ``ssh``/``scp`` with the usual
  non-interactive options and a shared ControlMaster socket so the
  per-command handshake cost is paid once per node.
* ``LocalCommandRunner`` — runs on this machine; the testing analog (the
  reference exercises runner logic through its fake-multinode docker
  provider; a local shell is the dependency-free equivalent).

``ManagedVMProvider`` composes them into a provider for a *static fleet*
of reachable machines (the reference's ``local`` node provider): create
= pick a free host, sync the bootstrap dir, run setup + start commands;
terminate = run the stop command and release the host.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Dict, List, Optional, Sequence

from .config import NodeTypeConfig
from .provider import NODE_TYPE_LABEL, PROVIDER_ID_LABEL, NodeProvider


class CommandRunner:
    """One target machine."""

    def run(self, cmd: str, timeout: float = 120.0) -> str:
        """Run a shell command; returns stdout, raises CalledProcessError
        on non-zero exit."""
        raise NotImplementedError

    def sync_up(self, local_path: str, remote_path: str) -> None:
        """Copy a local file/directory onto the target."""
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    def __init__(self, env: Optional[Dict[str, str]] = None):
        self._env = env

    def run(self, cmd: str, timeout: float = 120.0) -> str:
        env = dict(os.environ, **self._env) if self._env else None
        return subprocess.check_output(
            cmd, shell=True, text=True, timeout=timeout,
            stderr=subprocess.STDOUT, env=env,
        )

    def sync_up(self, local_path: str, remote_path: str) -> None:
        os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
        subprocess.check_call(["cp", "-r", local_path, remote_path])


class SSHCommandRunner(CommandRunner):
    """ssh/scp against one host.  Non-interactive (BatchMode), host keys
    auto-accepted (fresh VMs have fresh keys), connections multiplexed
    through a ControlMaster socket under /tmp so repeated setup commands
    don't re-handshake."""

    def __init__(self, host: str, user: Optional[str] = None,
                 key_path: Optional[str] = None, port: int = 22):
        self.host = host
        self.user = user
        self.key_path = key_path
        self.port = port
        self._control = os.path.join(
            tempfile.gettempdir(), f"rtpu-ssh-{user or 'x'}-{host}-{port}"
        )

    @property
    def _target(self) -> str:
        return f"{self.user}@{self.host}" if self.user else self.host

    def _base_opts(self) -> List[str]:
        opts = [
            "-o", "BatchMode=yes",
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "LogLevel=ERROR",
            "-o", "ControlMaster=auto",
            "-o", f"ControlPath={self._control}",
            "-o", "ControlPersist=60s",
            "-p", str(self.port),
        ]
        if self.key_path:
            opts += ["-i", self.key_path]
        return opts

    def run(self, cmd: str, timeout: float = 120.0) -> str:
        return subprocess.check_output(
            ["ssh", *self._base_opts(), self._target, cmd],
            text=True, timeout=timeout, stderr=subprocess.STDOUT,
        )

    def sync_up(self, local_path: str, remote_path: str) -> None:
        opts = self._base_opts()
        # scp spells the port flag -P.
        opts[opts.index("-p") ] = "-P"
        subprocess.check_call(
            ["scp", "-r", *opts, local_path, f"{self._target}:{remote_path}"]
        )


class ManagedVMProvider(NodeProvider):
    """Static fleet of reachable machines (reference ``local`` provider +
    command-runner bootstrap).  ``hosts`` maps host name → CommandRunner;
    commands are shell templates with ``{address}``, ``{labels}``,
    ``{resources}`` placeholders."""

    def __init__(
        self,
        hosts: Dict[str, CommandRunner],
        cp_address: str,
        start_command: str,
        # [r] bracket trick: the pattern must not match the shell that
        # runs the pkill itself (whose cmdline contains the pattern) —
        # without it the stop command SIGTERMs its own shell, and with a
        # LocalCommandRunner it would kill the driver's cluster too.
        stop_command: str = "pkill -f '[r]ay_tpu[.]core' || true",
        setup_commands: Sequence[str] = (),
        sync_dirs: Sequence[tuple] = (),
    ):
        self._runners = dict(hosts)
        self._free: List[str] = list(hosts)
        self._cp_address = cp_address
        self._start = start_command
        self._stop = stop_command
        self._setup = list(setup_commands)
        self._sync = list(sync_dirs)
        self._nodes: Dict[str, tuple] = {}  # provider_id -> (type, host)

    def create_node(self, node_type: NodeTypeConfig) -> str:
        import json
        import uuid

        if not self._free:
            raise RuntimeError("ManagedVMProvider: fleet exhausted")
        host = self._free.pop(0)
        runner = self._runners[host]
        provider_id = f"vm-{host}-{uuid.uuid4().hex[:6]}"
        labels = dict(node_type.labels)
        labels[NODE_TYPE_LABEL] = node_type.name
        labels[PROVIDER_ID_LABEL] = provider_id
        fmt = {
            "address": self._cp_address,
            "labels": json.dumps(labels),
            "resources": json.dumps(dict(node_type.resources)),
            "provider_id": provider_id,
        }
        try:
            for src, dst in self._sync:
                runner.sync_up(src, dst)
            for cmd in self._setup:
                runner.run(cmd.format(**fmt))
            runner.run(self._start.format(**fmt))
        except Exception:
            # A timed-out start may have actually launched the node —
            # stop best-effort before releasing the host, or the next
            # create_node double-provisions the machine.  Stop templates
            # get the SAME placeholder set as start/setup ({address},
            # {labels}, {resources}, {provider_id}) — formatting with
            # provider_id alone raised KeyError on richer templates and
            # silently skipped the cleanup.
            try:
                runner.run(self._stop.format(**fmt))
            except Exception:  # raylint: waive[RTL003] host unreachable; caller sees empty result
                pass
            self._free.insert(0, host)
            raise
        self._nodes[provider_id] = (node_type.name, host)
        return provider_id

    def terminate_node(self, provider_id: str) -> None:
        import json

        entry = self._nodes.pop(provider_id, None)
        if entry is None:
            return
        node_type, host = entry
        fmt = {
            "address": self._cp_address,
            "labels": json.dumps({NODE_TYPE_LABEL: node_type,
                                  PROVIDER_ID_LABEL: provider_id}),
            "resources": json.dumps({}),
            "provider_id": provider_id,
        }
        try:
            # The node-agent's argv carries its labels JSON, so a stop
            # command of ``pkill -f {provider_id}`` finds exactly this
            # node's processes.
            self._runners[host].run(self._stop.format(**fmt))
        finally:
            self._free.append(host)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return {pid: t for pid, (t, _) in self._nodes.items()}
